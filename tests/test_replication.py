"""Replicated read plane tier (ISSUE 17).

Covers the four layers the replication package stitches together:

* ``Journal.tail()`` / :class:`JournalTail` — the live WAL cursor followers
  poll: rotation rename races, torn ``.open`` tails (park, never skip, until
  the segment seals), writer ``truncate()`` detection by segment identity,
  and the exactly-once contract under a concurrent writer;
* epoch fencing (``controller/standing.py``) — the sidecar re-read refusal
  point: a promoted follower's ``fence(epoch+1)`` makes the deposed writer's
  next append raise :class:`FencedEpochError` *before* the WAL sees a
  stale-regime record, restarts re-fence cleanly, and ``recover()`` surfaces
  the newest epoch from sidecar + journaled stamps;
* :class:`ReplicationState` — the watch hub: idempotent record application,
  cursor catch-up / ring-falloff resync, long-poll wakeups, and the
  ``rebase()`` reconciliation after a tail reset;
* follower serving over real HTTP — stamped reads, refused mutations,
  long-poll WATCH delivery from writer append to follower watcher, and the
  lag-bound 503 with its derived Retry-After (liveness stays exempt).

Journal-level fault injection (``FaultPlan.torn_tail`` /
``lose_fsync_suffix`` / ``rotation_crash`` via :class:`ChaosJournal`) runs
under the ``chaos`` marker — deterministic, part of tier-1.  The
multi-process failover drill lives in ``tests/test_replication_drill.py``
(marked ``slow``, run by name in its own CI step).
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.backend.chaos import ChaosJournal, FaultPlan
from cruise_control_tpu.controller.standing import (
    ControllerJournal,
    FencedEpochError,
    StandingProposalSet,
)
from cruise_control_tpu.core.journal import (
    Journal,
    JournalTail,
    SimulatedCrash,
    _canonical,
    _crc,
)
from cruise_control_tpu.replication import ReplicationState

WINDOW_MS = 60_000
TRIMMED_GOALS = "RackAwareGoal,ReplicaCapacityGoal,ReplicaDistributionGoal"


def ids(records):
    return [r["i"] for r in records]


def encode_line(record: dict) -> str:
    """The exact on-disk envelope ``Journal.append`` writes (used to craft
    torn tails byte-for-byte)."""
    return json.dumps(
        {"c": _crc(_canonical(record)), "r": record}, separators=(",", ":")
    )


def some_proposals(n: int = 2):
    return [
        ExecutionProposal(
            tp=("T", i), partition_size=1.0, old_leader=0,
            old_replicas=(0, 1), new_replicas=(0, 2),
        )
        for i in range(n)
    ]


def standing_set(version: int, trigger: str = "drift") -> StandingProposalSet:
    return StandingProposalSet(
        version=version, created_ms=123, trigger=trigger, drift=2.0,
        proposals=some_proposals(), reaction_s=0.01,
    )


def published_record(version: int, epoch: int = 1, **extra) -> dict:
    rec = {
        "type": "published", "version": version, "epoch": epoch,
        "created_ms": 123, "trigger": "drift", "drift": 2.0,
        "reaction_s": 0.01, "proposals": [],
    }
    rec.update(extra)
    return rec


# -- the live WAL cursor ------------------------------------------------------


class TestJournalTail:
    def test_poll_returns_appends_in_order_and_catches_up(self, tmp_path):
        j = Journal(str(tmp_path), max_segment_records=4)
        for i in range(10):
            j.append({"i": i})
        t = j.tail()
        assert ids(t.poll()) == list(range(10))
        assert t.caught_up is True and t.records == 10
        assert t.poll() == []          # nothing new: still caught up
        j.append({"i": 10})
        assert ids(t.poll()) == [10]
        assert t.resets == 0 and t.skipped == 0

    def test_max_records_paginates_without_loss(self, tmp_path):
        j = Journal(str(tmp_path), max_segment_records=3)
        for i in range(8):
            j.append({"i": i})
        t = j.tail()
        got = []
        while True:
            page = t.poll(max_records=3)
            if not page:
                break
            assert len(page) <= 3
            got.extend(ids(page))
        assert got == list(range(8))

    def test_rotation_rename_race_resumes_under_sealed_name(self, tmp_path):
        """Cursor parked mid-``.open`` segment; the writer seals it (atomic
        rename, same inode); the next poll continues at the same byte offset
        under the sealed name — no miss, no double-delivery."""
        j = Journal(str(tmp_path), max_segment_records=3)
        j.append({"i": 0})
        j.append({"i": 1})
        t = j.tail()
        assert ids(t.poll()) == [0, 1]   # cursor now mid segment-000000.open
        j.append({"i": 2})               # fills the segment: rotation seals it
        j.append({"i": 3})               # lands in segment-000001.open
        assert os.path.exists(str(tmp_path / "segment-000000.jsonl"))
        assert ids(t.poll()) == [2, 3]
        assert t.resets == 0 and t.skipped == 0

    def test_torn_open_tail_parks_then_completes(self, tmp_path):
        """A torn (half-written) record at the end of the ``.open`` segment
        is a write in progress, not corruption: the cursor parks before it
        and delivers it whole once the writer finishes the line."""
        j = Journal(str(tmp_path))
        j.append({"i": 0})
        j.append({"i": 1})
        t = j.tail()
        assert ids(t.poll()) == [0, 1]
        line = encode_line({"i": 2})
        open_seg = str(tmp_path / "segment-000000.jsonl.open")
        with open(open_seg, "a") as fh:
            fh.write(line[: len(line) // 2])   # torn: no newline
        assert t.poll() == []
        assert t.skipped == 0                  # parked, NOT skipped
        with open(open_seg, "a") as fh:
            fh.write(line[len(line) // 2:] + "\n")
        assert ids(t.poll()) == [2]
        assert t.skipped == 0 and t.resets == 0

    def test_sealed_torn_tail_is_permanently_skipped(self, tmp_path):
        """Once a crashed writer's torn tail is sealed into a final segment
        (restart recovery), it can never complete: the cursor skips it for
        good and the WAL keeps flowing."""
        j = Journal(str(tmp_path))
        for i in range(3):
            j.append({"i": i})
        line = encode_line({"i": 99})
        with open(str(tmp_path / "segment-000000.jsonl.open"), "a") as fh:
            fh.write(line[: len(line) // 2])
        # restart: a fresh writer seals the leftover .open (torn tail and all)
        j2 = Journal(str(tmp_path))
        assert os.path.exists(str(tmp_path / "segment-000000.jsonl"))
        t = JournalTail(str(tmp_path))
        assert ids(t.poll()) == [0, 1, 2]
        assert t.skipped == 1                  # the torn line, permanently
        j2.append({"i": 3})                    # next segment: not wedged
        assert ids(t.poll()) == [3]
        assert t.resets == 0

    def test_truncate_resets_cursor_and_redelivers(self, tmp_path):
        j = Journal(str(tmp_path), max_segment_records=3)
        for i in range(5):
            j.append({"i": i})
        t = j.tail()
        assert ids(t.poll()) == list(range(5))
        j.truncate()                           # writer-side compaction
        j.append({"i": 100})
        j.append({"i": 101})
        got = []
        deadline = time.monotonic() + 5.0
        while len(got) < 2 and time.monotonic() < deadline:
            got.extend(ids(t.poll()))          # reset pass, then re-delivery
        assert got == [100, 101]               # the new WAL regime, whole
        assert t.resets == 1

    def test_concurrent_writer_exactly_once_in_order(self, tmp_path):
        """Satellite regression: a cursor polling concurrently with a writer
        that rotates every 7 records must deliver every record exactly once,
        in write order — the rotation rename race and the torn-flush window
        are both crossed hundreds of times."""
        n = 300
        j = Journal(str(tmp_path), max_segment_records=7)
        t = j.tail()
        stop = threading.Event()

        def writer():
            for i in range(n):
                j.append({"i": i})
            stop.set()

        thr = threading.Thread(target=writer)
        thr.start()
        got = []
        deadline = time.monotonic() + 60.0
        while len(got) < n and time.monotonic() < deadline:
            got.extend(ids(t.poll()))
        thr.join(timeout=30)
        got.extend(ids(t.poll()))
        assert got == list(range(n))
        assert t.resets == 0 and t.skipped == 0

    def test_replay_iter_survives_rotation_rename_race(self, tmp_path):
        """Satellite fix: ``replay_iter`` captures the segment listing once;
        a segment sealed between the listing and its ``open()`` is retried
        under the final name (same inode, same bytes) — exactly once."""
        j = Journal(str(tmp_path), max_segment_records=3)
        for i in range(5):
            j.append({"i": i})   # seg0 sealed [0,1,2]; seg1.open [3,4]
        counts: dict = {}
        it = j.replay_iter(counts)
        first = next(it)         # listing captured: [seg0, seg1.jsonl.open]
        assert first["i"] == 0
        j.append({"i": 5})       # seals seg1 under the iterator's feet
        assert not os.path.exists(str(tmp_path / "segment-000001.jsonl.open"))
        rest = [r["i"] for r in it]
        assert [first["i"]] + rest == [0, 1, 2, 3, 4, 5]
        assert counts["skipped"] == 0 and counts["segments"] == 2


# -- epoch fencing ------------------------------------------------------------


class TestEpochFencing:
    def _journal(self, tmp_path) -> ControllerJournal:
        return ControllerJournal(Journal(str(tmp_path / "controller")))

    def test_stale_epoch_append_refused_after_promotion(self, tmp_path):
        """The deposed writer's next append dies at the sidecar re-read —
        before the WAL (and every follower) can see a stale-regime record."""
        old = self._journal(tmp_path)
        old.fence(1)
        old.published(standing_set(1))
        # a promoted follower on the same directory: recover, fence epoch+1
        new = self._journal(tmp_path)
        standing, _, _, epoch = new.recover()
        assert standing is not None and standing.version == 1
        assert epoch == 1
        new.fence(epoch + 1)
        with pytest.raises(FencedEpochError) as exc:
            old.published(standing_set(2))
        assert exc.value.epoch == 1 and exc.value.current == 2
        # the refused record never reached the WAL
        recovered, _, _, _ = self._journal(tmp_path).recover()
        assert recovered.version == 1
        # the new holder writes fine
        new.published(standing_set(2))

    def test_restart_refences_cleanly(self, tmp_path):
        j = self._journal(tmp_path)
        j.fence(1)
        j.published(standing_set(1))
        # restart: recover + fence(epoch+1) — monotonic, never backwards
        j2 = self._journal(tmp_path)
        _, _, _, epoch = j2.recover()
        j2.fence(epoch + 1)
        assert j2.epoch == 2 and j2.read_fence() == 2
        # re-fencing the SAME epoch is idempotent (a retried startup)
        j2.fence(2)
        assert j2.read_fence() == 2
        # fencing backwards is refused
        with pytest.raises(FencedEpochError):
            j2.fence(1)
        j2.published(standing_set(2))

    def test_recover_surfaces_newest_epoch(self, tmp_path):
        j = self._journal(tmp_path)
        j.fence(1)
        j.published(standing_set(1))
        j.fence(3)
        _, _, _, epoch = self._journal(tmp_path).recover()
        assert epoch == 3
        # sidecar lost (partial directory copy): the journaled epoch records
        # and per-record stamps still carry the regime
        os.remove(str(tmp_path / "controller" / ControllerJournal.FENCE_FILE))
        fresh = self._journal(tmp_path)
        _, _, _, epoch = fresh.recover()
        assert epoch == 3
        assert fresh.epoch == 3   # installed: stale writes still refused


# -- the watch hub ------------------------------------------------------------


class TestReplicationState:
    def test_apply_is_idempotent_and_absorbs_regressions(self):
        s = ReplicationState()
        s.apply(published_record(2))
        assert s.set_version == 2 and s.seq == 1
        s.apply(published_record(2))    # duplicate delivery (tail reset)
        s.apply(published_record(1))    # version regression (compaction)
        assert s.set_version == 2 and s.seq == 1   # no delta for either
        s.apply(published_record(3, superseded=2))
        assert s.set_version == 3 and s.seq == 2

    def test_epoch_records_emit_once(self):
        s = ReplicationState()
        s.apply({"type": "epoch", "epoch": 2})
        s.apply({"type": "epoch", "epoch": 2})   # duplicate: absorbed
        s.apply({"type": "epoch", "epoch": 1})   # stale: absorbed
        assert s.epoch == 2 and s.seq == 1
        deltas, _, _ = s.watch(0, 0.0)
        assert [d["kind"] for d in deltas] == ["epoch"]

    def test_watch_cursor_catch_up(self):
        s = ReplicationState()
        for v in (1, 2, 3):
            s.apply(published_record(v))
        deltas, nxt, resync = s.watch(0, 0.0)
        assert [d["version"] for d in deltas] == [1, 2, 3]
        assert nxt == 3 and resync is False
        deltas, nxt2, resync = s.watch(nxt, 0.0)
        assert deltas == [] and nxt2 == 3 and resync is False
        # partial cursor: only the missed suffix comes back
        deltas, _, _ = s.watch(1, 0.0)
        assert [d["version"] for d in deltas] == [2, 3]

    def test_watch_ring_falloff_resyncs_with_snapshot(self):
        s = ReplicationState(ring_size=4)
        for v in range(1, 11):
            s.apply(published_record(v))
        deltas, nxt, resync = s.watch(1, 0.0)   # seq 2 fell off the ring
        assert resync is True
        assert len(deltas) == 1 and deltas[0]["kind"] == "published"
        assert deltas[0]["version"] == 10       # snapshot of the current set
        assert nxt == s.seq
        # the watcher continues normally from the resync cursor
        s.apply(published_record(11))
        deltas, _, resync = s.watch(nxt, 0.0)
        assert resync is False and [d["version"] for d in deltas] == [11]

    def test_watch_future_cursor_resyncs_immediately(self):
        """A cursor from a previous follower incarnation (seq reset) must
        resync at once, not stall until timeout."""
        s = ReplicationState()
        s.apply(published_record(5))
        t0 = time.monotonic()
        deltas, nxt, resync = s.watch(999, 5.0)
        assert time.monotonic() - t0 < 1.0
        assert resync is True and nxt == s.seq
        assert deltas[0]["version"] == 5

    def test_watch_long_poll_wakes_on_delta(self):
        s = ReplicationState()
        s.apply(published_record(1))
        _, since, _ = s.watch(0, 0.0)

        def publish_later():
            time.sleep(0.15)
            s.apply(published_record(2))

        threading.Thread(target=publish_later).start()
        t0 = time.monotonic()
        deltas, _, resync = s.watch(since, 10.0)
        assert time.monotonic() - t0 < 5.0     # woke, did not ride timeout
        assert [d["version"] for d in deltas] == [2] and resync is False

    def test_rebase_drained_truncate_clears_the_set(self):
        """The writer drained + truncated before our poll saw the drain
        record: the re-delivered WAL is empty — the set is gone and watchers
        hear about it."""
        s = ReplicationState()
        s.apply(published_record(2))
        s.rebase([])
        assert s.standing is None
        deltas, _, _ = s.watch(1, 0.0)
        assert [d["kind"] for d in deltas] == ["drained"]

    def test_rebase_fresh_wal_regime_serves_lower_version(self):
        """Operator wiped the directory: the recovered version is BELOW ours
        — serve it (an empty-handed follower is worse), via an explicit
        published delta rather than a silent regression."""
        s = ReplicationState()
        s.apply(published_record(5))
        s.rebase([published_record(3)])
        assert s.standing is not None and s.standing.version == 3
        # compaction re-delivering the current set is a no-op
        seq = s.seq
        s.rebase([published_record(3)])
        assert s.seq == seq

    def test_stamp_staleness_and_degraded(self):
        w = ReplicationState(writer=True)
        assert w.stamp()["role"] == "writer"
        assert w.stamp()["stalenessMs"] == 0     # writer: zero by construction
        f = ReplicationState()
        f.apply(published_record(1))
        st = f.stamp(degraded_after_ms=10_000)
        assert st["role"] == "follower" and st["setVersion"] == 1
        assert st["degraded"] is False
        f.last_poll_ms -= 60_000                 # tail poll stalled
        assert f.stamp()["stalenessMs"] >= 60_000
        f.last_activity_ms -= 60_000             # no records: writer is gone
        assert f.stamp(degraded_after_ms=10_000)["degraded"] is True


# -- journal-level fault injection (ChaosJournal) -----------------------------


@pytest.mark.chaos
class TestChaosJournalFaults:
    def test_torn_tail_fault_recovers_clean_prefix(self, tmp_path):
        plan = FaultPlan(seed=7).torn_tail(after_appends=2)
        j = ChaosJournal(str(tmp_path), plan=plan)
        j.append({"i": 0})
        j.append({"i": 1})
        t = JournalTail(str(tmp_path))
        assert ids(t.poll()) == [0, 1]
        with pytest.raises(SimulatedCrash):
            j.append({"i": 2})               # dies mid-record, torn prefix
        assert [k for k, _ in j.fault_log] == ["torn_tail"]
        # a live cursor parks on the torn .open tail — in-progress, not junk
        assert t.poll() == [] and t.skipped == 0
        # restart: recovery seals the wreck; replay = the clean prefix
        j2 = Journal(str(tmp_path))
        replayed = j2.replay()
        assert ids(replayed) == [0, 1]
        assert replayed.skipped == 1
        # the sealed torn line becomes a permanent skip; the WAL flows on
        j2.append({"i": 2})
        assert ids(t.poll()) == [2]
        assert t.skipped == 1 and t.resets == 0

    def test_fsync_lost_suffix_shrinks_to_survivors(self, tmp_path):
        """Process death with the page-cache suffix unflushed: the last
        ``lose`` records evaporate.  Recovery serves the survivors; a cursor
        that already read the doomed suffix detects the shrink (same inode,
        smaller size) and resets rather than serving a stale offset."""
        plan = FaultPlan(seed=7).lose_fsync_suffix(after_appends=3, lose=2)
        j = ChaosJournal(str(tmp_path), plan=plan)
        for i in range(3):
            j.append({"i": i})
        t = JournalTail(str(tmp_path))
        assert ids(t.poll()) == [0, 1, 2]    # includes the doomed suffix
        with pytest.raises(SimulatedCrash):
            j.append({"i": 3})
        assert ids(Journal(str(tmp_path)).replay()) == [0]
        got = []
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and t.resets == 0:
            got.extend(ids(t.poll()))
        got.extend(ids(t.poll()))
        assert t.resets == 1
        assert got == [0]                    # prefix re-delivered, no junk

    def test_rotation_crash_strands_then_seals_full_segment(self, tmp_path):
        """Death between a rotation's close and its rename strands a COMPLETE
        segment under its .open name: nothing is lost, recovery seals it, and
        a live cursor crosses the transition without a reset."""
        plan = FaultPlan(seed=7).rotation_crash(rotation_no=1)
        j = ChaosJournal(str(tmp_path), plan=plan, max_segment_records=3)
        j.append({"i": 0})
        j.append({"i": 1})
        t = JournalTail(str(tmp_path))
        assert ids(t.poll()) == [0, 1]
        with pytest.raises(SimulatedCrash):
            j.append({"i": 2})               # record written; rotation dies
        assert os.path.exists(str(tmp_path / "segment-000000.jsonl.open"))
        assert ids(t.poll()) == [2]          # the stranded record still reads
        # restart seals the stranded segment and continues in the next one
        j2 = Journal(str(tmp_path), max_segment_records=3)
        assert os.path.exists(str(tmp_path / "segment-000000.jsonl"))
        j2.append({"i": 3})
        assert ids(t.poll()) == [3]
        assert t.resets == 0 and t.skipped == 0
        assert ids(Journal(str(tmp_path)).replay()) == [0, 1, 2, 3]


# -- follower serving over real HTTP ------------------------------------------


def base_props(**overrides):
    props = {
        "partition.metrics.window.ms": WINDOW_MS,
        "num.partition.metrics.windows": 4,
        "metric.sampling.interval.ms": 3_600_000,
        "anomaly.detection.interval.ms": 3_600_000,
        "anomaly.detection.initial.pass": False,
        "broker.capacity.config.resolver.class":
            "cruise_control_tpu.monitor.capacity.StaticCapacityResolver",
        "sample.store.class":
            "cruise_control_tpu.monitor.samplestore.NoopSampleStore",
        "webserver.http.port": 0,
        "min.valid.partition.ratio": 0.5,
        "default.goals": TRIMMED_GOALS,
    }
    props.update(overrides)
    return props


def seeded_backend(num_brokers=4, partitions=12):
    from cruise_control_tpu.backend import FakeClusterBackend

    backend = FakeClusterBackend()
    for b in range(num_brokers):
        backend.add_broker(b, rack=str(b % 2))
    for p in range(partitions):
        backend.create_partition(
            ("T", p), [p % 2, (p % 2 + 1) % num_brokers],
            load=[1.5, 4e3, 6e3, 3e4],
        )
    return backend


def make_app(**overrides):
    from cruise_control_tpu.app import CruiseControlTpuApp
    from cruise_control_tpu.core.resources import Resource
    from cruise_control_tpu.monitor.capacity import StaticCapacityResolver

    app = CruiseControlTpuApp(base_props(**overrides), backend=seeded_backend())
    app.monitor.capacity_resolver = StaticCapacityResolver(
        {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
         Resource.DISK: 1e7}
    )
    return app


def http_get(port: int, path: str, timeout: float = 30.0):
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{port}/kafkacruisecontrol/{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            body = json.loads(raw) if raw else {}
        except ValueError:
            body = {}
        return e.code, dict(e.headers), body


def http_post(port: int, path: str, timeout: float = 30.0):
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{port}/kafkacruisecontrol/{path}"
    req = urllib.request.Request(url, method="POST", data=b"")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            body = json.loads(raw) if raw else {}
        except ValueError:
            body = {}
        return e.code, dict(e.headers), body


def poll_until(pred, timeout_s=20.0, desc="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {desc}")


@pytest.fixture(scope="module")
def repl_pair(tmp_path_factory):
    """One writer app (controller enabled, fenced WAL) + one follower app
    tailing the same journal directory, both serving HTTP in-process."""
    jdir = str(tmp_path_factory.mktemp("repl"))
    writer = make_app(**{
        "journal.dir": jdir,
        "controller.enable": True,
        "controller.tick.interval.ms": 3_600_000,
        "replication.role": "writer",
    })
    writer.start(serve_http=True)
    follower = make_app(**{
        "journal.dir": jdir,
        "replication.role": "follower",
        "replication.poll.interval.ms": 20,
    })
    follower.start(serve_http=True)
    yield writer, follower
    follower.stop()
    writer.stop()


class TestFollowerServing:
    def test_roles_epoch_and_stamped_reads(self, repl_pair):
        writer, follower = repl_pair
        # the writer's startup recovery fenced epoch 1; the follower's first
        # synchronous tail poll already saw the epoch record
        _, _, body = http_get(writer.port, "state?substates=controller")
        assert body["replication"]["role"] == "writer"
        poll_until(lambda: follower._replication.epoch == 1,
                   desc="follower sees the fence record")
        status, _, body = http_get(follower.port, "state?substates=controller")
        assert status == 200
        stamp = body["replication"]
        assert stamp["role"] == "follower" and stamp["epoch"] == 1
        assert stamp["degraded"] is False

    def test_follower_refuses_mutations_with_retry_after(self, repl_pair):
        _, follower = repl_pair
        status, headers, body = http_post(
            follower.port, "rebalance?dryrun=true&json=true"
        )
        assert status == 503
        assert float(headers.get("Retry-After")) >= 1
        assert "follower" in json.dumps(body)

    def test_publish_propagates_to_follower_watch(self, repl_pair):
        writer, follower = repl_pair
        # write-path publish on the writer's fenced journal: the in-process
        # listener stamps the writer's own view synchronously...
        writer.controller.journal.published(standing_set(1))
        _, _, body = http_get(writer.port, "watch?since=0&timeout_ms=0")
        assert any(
            d["kind"] == "published" and d["version"] == 1
            for d in body["deltas"]
        )
        assert body["replication"]["setVersion"] == 1
        # ...and the follower's tailer folds the same bytes within its poll
        # cadence, visible through a long-poll WATCH
        deadline = time.monotonic() + 20.0
        since, seen = 0, []
        while time.monotonic() < deadline:
            _, _, body = http_get(
                follower.port, f"watch?since={since}&timeout_ms=1000"
            )
            seen.extend(body["deltas"])
            since = body["since"]
            if any(d["kind"] == "published" and d["version"] == 1
                   for d in seen):
                break
        assert any(d["kind"] == "published" and d["version"] == 1
                   for d in seen), seen
        poll_until(
            lambda: http_get(follower.port, "state?substates=controller")
            [2]["replication"]["setVersion"] == 1,
            desc="follower stamp converges to v1",
        )

    def test_long_poll_wakes_within_publish_latency(self, repl_pair):
        writer, follower = repl_pair
        _, _, body = http_get(follower.port, "watch?since=0&timeout_ms=0")
        since = body["since"]

        def publish_later():
            time.sleep(0.2)
            writer.controller.journal.published(standing_set(2))

        threading.Thread(target=publish_later).start()
        t0 = time.monotonic()
        status, _, body = http_get(
            follower.port, f"watch?since={since}&timeout_ms=15000"
        )
        wall = time.monotonic() - t0
        assert status == 200
        assert any(d["kind"] == "published" and d["version"] == 2
                   for d in body["deltas"])
        assert wall < 10.0     # woke on the delta, did not ride the timeout

    def test_lag_bound_503_with_derived_retry_after(self, repl_pair):
        """A follower whose tail poll stalls past replication.lag.bound.ms
        refuses staleness-sensitive reads with 503 + a staleness-derived
        Retry-After; liveness stays exempt."""
        _, follower = repl_pair
        follower._follower_tailer.stop()
        try:
            follower._replication.last_poll_ms -= 60_000
            status, headers, _ = http_get(
                follower.port, "state?substates=controller"
            )
            assert status == 503
            assert float(headers.get("Retry-After")) >= 1
            status, _, _ = http_get(follower.port, "healthz")
            assert status == 200   # liveness never gated on replica lag
        finally:
            follower._replication.note_poll()
            follower._follower_tailer._stop.clear()
            follower._follower_tailer.start()
        status, _, _ = http_get(follower.port, "state?substates=controller")
        assert status == 200
