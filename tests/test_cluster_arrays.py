"""Model-layer tests: array snapshots, load math, mutations, stats, diff.

Reference behavior: ClusterModelTest / DeterministicClusterTest model assertions.
"""

import numpy as np
import pytest

import fixtures
from cruise_control_tpu.core.resources import DerivedResource, Resource
from cruise_control_tpu.model import arrays as A
from cruise_control_tpu.model import stats as S
from cruise_control_tpu.model.cluster import BrokerState
from cruise_control_tpu.model.model_utils import follower_cpu_from_leader_load
from cruise_control_tpu.analyzer.proposals import diff


def test_unbalanced_broker_loads():
    state, maps = fixtures.unbalanced().to_arrays()
    load = np.asarray(A.broker_load(state))
    # both partitions lead on broker 0 with load (50, 150000, 100000, 150000)
    np.testing.assert_allclose(load[0], [100.0, 300000.0, 200000.0, 300000.0], rtol=1e-5)
    np.testing.assert_allclose(load[1], 0.0)
    np.testing.assert_allclose(load[2], 0.0)
    assert maps.broker_ids == [0, 1, 2]
    assert state.num_racks == 2


def test_leadership_index_derivation():
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    lead = np.asarray(A.is_leader(state))
    assert lead.sum() == 1
    leader_row = int(np.asarray(state.partition_leader)[0])
    assert np.asarray(state.replica_broker)[leader_row] == maps.broker_index[0]


def test_effective_load_reconstructs_measured():
    """base + is_leader*delta must reproduce the measured loads exactly."""
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    eff = np.asarray(A.effective_load(state))
    by_broker = {maps.broker_index[b]: b for b in maps.broker_ids}
    rb = np.asarray(state.replica_broker)
    for row in range(state.num_replicas):
        broker_id = by_broker[rb[row]]
        if broker_id == 0:
            np.testing.assert_allclose(eff[row], [40.0, 100.0, 130.0, 75.0], rtol=1e-5)
        elif broker_id == 1:
            np.testing.assert_allclose(eff[row], [5.0, 100.0, 0.0, 75.0], rtol=1e-5)


def test_leadership_relocation_transfers_nwout_and_cpu_fraction():
    """relocateLeadership semantics (ClusterModel.java:409): whole NW_OUT + CPU
    fraction move to the destination."""
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    rb = np.asarray(state.replica_broker)
    follower_row = int(np.nonzero(rb == maps.broker_index[1])[0][0])
    moved = A.relocate_leadership(state, np.array([0]), np.array([follower_row]))

    load = np.asarray(A.broker_load(moved))
    follower_cpu_est = follower_cpu_from_leader_load(100.0, 130.0, 40.0)
    delta_cpu = 40.0 - follower_cpu_est
    # old leader keeps follower-equivalent load
    np.testing.assert_allclose(load[0], [follower_cpu_est, 100.0, 0.0, 75.0], rtol=1e-5)
    # new leader gains full NW_OUT + CPU delta
    np.testing.assert_allclose(load[1], [5.0 + delta_cpu, 100.0, 130.0, 75.0], rtol=1e-5)
    # NW_IN and DISK untouched by leadership moves
    np.testing.assert_allclose(load[:, Resource.DISK].sum(), 150.0, rtol=1e-5)


def test_relocate_replicas():
    state, maps = fixtures.unbalanced().to_arrays()
    moved = A.relocate_replicas(state, np.array([0]), np.array([maps.broker_index[2]]))
    load = np.asarray(A.broker_load(moved))
    np.testing.assert_allclose(load[0], [50.0, 150000.0, 100000.0, 150000.0], rtol=1e-5)
    np.testing.assert_allclose(load[2], [50.0, 150000.0, 100000.0, 150000.0], rtol=1e-5)
    # negative index is a no-op
    same = A.relocate_replicas(state, np.array([-1]), np.array([1]))
    np.testing.assert_array_equal(
        np.asarray(same.replica_broker), np.asarray(state.replica_broker)
    )


def test_swap_replicas():
    state, maps = fixtures.unbalanced_with_a_follower().to_arrays()
    rb0 = np.asarray(state.replica_broker).copy()
    rows = np.nonzero(rb0 != rb0[0])[0]
    other = int(rows[0])
    swapped = A.swap_replicas(state, np.array([0]), np.array([other]))
    rb1 = np.asarray(swapped.replica_broker)
    assert rb1[0] == rb0[other] and rb1[other] == rb0[0]


def test_potential_nw_out():
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    pnw = np.asarray(A.potential_nw_out(state))
    # every replica contributes its partition-leader's NW_OUT (130)
    np.testing.assert_allclose(pnw[maps.broker_index[0]], 130.0, rtol=1e-5)
    np.testing.assert_allclose(pnw[maps.broker_index[1]], 130.0, rtol=1e-5)
    np.testing.assert_allclose(pnw[maps.broker_index[2]], 0.0)


def test_utilization_matrix_rows():
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    m = np.asarray(A.utilization_matrix(state))
    b0, b1 = maps.broker_index[0], maps.broker_index[1]
    assert m[DerivedResource.CPU, b0] == pytest.approx(40.0, rel=1e-5)
    assert m[DerivedResource.LEADER_NW_IN, b0] == pytest.approx(100.0, rel=1e-5)
    assert m[DerivedResource.FOLLOWER_NW_IN, b1] == pytest.approx(100.0, rel=1e-5)
    assert m[DerivedResource.NW_OUT, b0] == pytest.approx(130.0, rel=1e-5)
    assert m[DerivedResource.PNW_OUT, b1] == pytest.approx(130.0, rel=1e-5)
    assert m[DerivedResource.LEADER_REPLICAS, b0] == 1.0
    assert m[DerivedResource.REPLICAS].sum() == 2.0


def test_rack_partition_counts():
    state, _ = fixtures.rack_aware_satisfiable().to_arrays()
    counts = np.asarray(A.replicas_per_rack_per_partition(state))
    # both replicas in rack '0' -> rack-aware violation visible as count 2
    assert counts.tolist() == [[2, 0]]


def test_dead_broker_offline_replicas():
    cluster = fixtures.unbalanced()
    cluster.set_broker_state(1, BrokerState.DEAD)
    state, maps = cluster.to_arrays()
    assert not bool(np.asarray(state.broker_alive)[maps.broker_index[1]])
    # no replicas on broker 1 in this fixture; mark broker 0 dead via array op
    state2 = A.set_broker_state(state, maps.broker_index[0], alive=False)
    offline = np.asarray(state2.replica_offline_mask())
    assert offline.sum() == 2  # both replicas live on broker 0


def test_jbod_disks_and_disk_death():
    logdirs = {"/d0": 150000.0, "/d1": 150000.0}
    cluster = fixtures.homogeneous_cluster(fixtures.RACK_BY_BROKER, logdirs=logdirs)
    cluster.create_replica(0, ("T1", 0), 0, True, logdir="/d0")
    cluster.set_replica_load(0, ("T1", 0), fixtures.load(10.0, 5.0, 5.0, 1000.0))
    cluster.create_replica(0, ("T1", 1), 0, True, logdir="/d1")
    cluster.set_replica_load(0, ("T1", 1), fixtures.load(10.0, 5.0, 5.0, 2000.0))
    state, maps = cluster.to_arrays()
    assert state.num_disks == 6
    dl = np.asarray(A.disk_load(state))
    assert dl[maps.disk_index[(0, "/d0")]] == pytest.approx(1000.0)
    assert dl[maps.disk_index[(0, "/d1")]] == pytest.approx(2000.0)

    cluster.mark_disk_dead(0, "/d0")
    assert cluster.broker_state(0) == BrokerState.BAD_DISKS
    state2, maps2 = cluster.to_arrays()
    offline = np.asarray(state2.replica_offline_mask())
    assert offline.sum() == 1

    # a cross-broker move resets the logdir assignment: the source disk stops
    # being charged and the dead source disk no longer marks the replica offline
    moved = A.relocate_replicas(state2, np.array([0]), np.array([maps2.broker_index[1]]))
    assert int(np.asarray(moved.replica_disk)[0]) == -1
    assert np.asarray(moved.replica_offline_mask()).sum() == 0
    dl2 = np.asarray(A.disk_load(moved))
    assert dl2[maps2.disk_index[(0, "/d0")]] == pytest.approx(0.0)


def test_padding_rows_are_inert():
    state, _ = fixtures.unbalanced().to_arrays(pad_replicas_to=16)
    assert state.num_replicas == 16
    assert np.asarray(state.replica_valid).sum() == 2
    load = np.asarray(A.broker_load(state))
    np.testing.assert_allclose(load[0], [100.0, 300000.0, 200000.0, 300000.0], rtol=1e-5)


def test_cluster_stats():
    state, _ = fixtures.unbalanced().to_arrays()
    st = S.cluster_model_stats(state, balance_percentage=1.1)
    np.testing.assert_allclose(np.asarray(st["util_avg"])[Resource.CPU], 100.0 / 3, rtol=1e-5)
    assert float(np.asarray(st["util_max"])[Resource.CPU]) == pytest.approx(100.0)
    assert float(np.asarray(st["util_min"])[Resource.CPU]) == 0.0
    assert int(st["num_alive_brokers"]) == 3
    assert int(st["total_replicas"]) == 2
    # nobody is inside the balance band around avg=33.3 (brokers are 100/0/0)
    assert np.asarray(st["num_balanced_by_resource"])[Resource.CPU] == 0
    std = float(S.utilization_std(state, Resource.CPU))
    assert std == pytest.approx(np.std([100.0, 0.0, 0.0]), rel=1e-5)


def test_diff_empty_when_unchanged():
    state, maps = fixtures.unbalanced().to_arrays()
    assert diff(state, state, maps) == []


def test_diff_replica_move_and_leadership():
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    rb = np.asarray(state.replica_broker)
    follower_row = int(np.nonzero(rb == maps.broker_index[1])[0][0])
    # move follower 1 -> 2, then make it leader
    final = A.relocate_replicas(state, np.array([follower_row]), np.array([maps.broker_index[2]]))
    final = A.relocate_leadership(final, np.array([0]), np.array([follower_row]))
    props = diff(state, final, maps)
    assert len(props) == 1
    p = props[0]
    assert p.tp == ("T1", 0)
    assert p.old_leader == 0
    assert p.new_leader == 2
    assert p.old_replicas == (0, 1)
    assert set(p.new_replicas) == {0, 2}
    assert p.new_replicas[0] == 2
    assert p.replicas_to_add == (2,)
    assert p.replicas_to_remove == (1,)
    assert p.has_leader_action and p.has_replica_action


def test_diff_leadership_only():
    state, maps = fixtures.rack_aware_satisfiable().to_arrays()
    rb = np.asarray(state.replica_broker)
    follower_row = int(np.nonzero(rb == maps.broker_index[1])[0][0])
    final = A.relocate_leadership(state, np.array([0]), np.array([follower_row]))
    props = diff(state, final, maps)
    assert len(props) == 1
    assert props[0].has_leader_action and not props[0].has_replica_action
    assert props[0].new_leader == 1


def test_host_model_queries():
    cluster = fixtures.rack_aware_satisfiable()
    assert cluster.replica_distribution() == {("T1", 0): [0, 1]}
    assert cluster.leader_distribution() == {("T1", 0): 0}
    cluster.delete_replica(1, ("T1", 0))
    assert cluster.replica_distribution() == {("T1", 0): [0]}
    with pytest.raises(ValueError):
        cluster.delete_replica(1, ("T1", 0))
    with pytest.raises(ValueError):
        cluster.create_replica(0, ("T1", 0), 0, True)  # duplicate on same broker
    with pytest.raises(ValueError):
        cluster.create_replica(2, ("T1", 0), 2, True)  # second leader
