"""Metric taxonomy tests (reference behavior: RawMetricType / KafkaMetricDef)."""

from cruise_control_tpu.core.metricdef import (
    BROKER_METRIC_DEF,
    COMMON_METRIC_DEF,
    COMMON_METRIC_NAMES,
    MetricScope,
    RawMetricType,
    ValueStrategy,
    raw_metric_scope,
    raw_types_for_scope,
    resource_to_metric_ids,
)
from cruise_control_tpu.core.resources import Resource


def test_raw_taxonomy_counts():
    # Reference RawMetricType: broker/topic/partition scopes; 43 broker types was the
    # historical figure, the current tree carries the full queue/local/total-time
    # percentile families.
    assert len(raw_types_for_scope(MetricScope.PARTITION)) == 1
    assert len(raw_types_for_scope(MetricScope.TOPIC)) == 7
    assert len(raw_types_for_scope(MetricScope.BROKER)) >= 40
    assert raw_metric_scope(RawMetricType.PARTITION_SIZE) is MetricScope.PARTITION


def test_common_def_is_prefix_of_broker_def():
    common = [m.name for m in COMMON_METRIC_DEF.all()]
    broker = [m.name for m in BROKER_METRIC_DEF.all()]
    assert common == COMMON_METRIC_NAMES
    assert broker[: len(common)] == common
    # ids are dense column indices
    assert [m.id for m in BROKER_METRIC_DEF.all()] == list(range(BROKER_METRIC_DEF.size()))


def test_strategies():
    assert COMMON_METRIC_DEF.metric_info("DISK_USAGE").strategy is ValueStrategy.LATEST
    assert COMMON_METRIC_DEF.metric_info("CPU_USAGE").strategy is ValueStrategy.AVG
    # All broker-only defs use AVG in the reference (KafkaMetricDef.java:61-101).
    assert (
        BROKER_METRIC_DEF.metric_info("BROKER_PRODUCE_TOTAL_TIME_MS_MAX").strategy
        is ValueStrategy.AVG
    )
    # Only CPU_USAGE is the CPU-model prediction target.
    assert COMMON_METRIC_DEF.metric_info("CPU_USAGE").to_predict
    assert not COMMON_METRIC_DEF.metric_info("DISK_USAGE").to_predict


def test_resource_groups():
    groups = resource_to_metric_ids(COMMON_METRIC_DEF)
    assert groups[Resource.CPU] == [COMMON_METRIC_DEF.metric_info("CPU_USAGE").id]
    assert groups[Resource.DISK] == [COMMON_METRIC_DEF.metric_info("DISK_USAGE").id]
    assert len(groups[Resource.NW_IN]) == 2   # leader bytes in + replication bytes in
    assert len(groups[Resource.NW_OUT]) == 2


def test_resource_properties():
    assert Resource.CPU.is_host_resource
    assert not Resource.DISK.is_host_resource
    assert Resource.DISK.is_broker_resource
    assert Resource.CPU.epsilon(1e6, 1e6) > 0
