"""Optional/auxiliary goal tests: PreferredLeaderElection, RackAwareDistribution,
TopicLeaderReplicaDistribution, BrokerSetAware, kafka-assigner compatibility.

These are the reference's non-default goals (``analyzer/goals/`` optional set +
``analyzer/kafkaassigner/``): each test builds a deterministic fixture violating
exactly one goal and asserts the goal's own optimization fixes it without
breaking the invariants of any prior goal.
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
from cruise_control_tpu.analyzer import goals_base as G
from cruise_control_tpu.core.resources import Resource
from cruise_control_tpu.model import arrays as A

from tests import fixtures

PAD = dict(pad_replicas_to=16, pad_partitions_to=8, pad_topics_to=2)


def ctx_for(state, **kw):
    return GoalContext.build(state.num_topics, state.num_brokers, **kw)


class TestPreferredLeaderElection:
    def test_leadership_returns_to_replica_list_head(self):
        cluster = fixtures.homogeneous_cluster({0: "0", 1: "1", 2: "0"})
        # two partitions, leader deliberately on the SECOND replica
        for i in range(2):
            cluster.create_replica(0, ("T1", i), 0, False)
            cluster.create_replica(1, ("T1", i), 1, True)
            cluster.set_replica_load(0, ("T1", i), fixtures.load(1, 10, 10, 100))
            cluster.set_replica_load(1, ("T1", i), fixtures.load(1, 10, 10, 100))
        state, maps = cluster.to_arrays(**PAD)
        ctx = ctx_for(state)
        opt = GoalOptimizer(goal_ids=(G.PREFERRED_LEADER_ELECTION,))
        final, result = opt.optimize(state, ctx, maps=maps)
        assert result.violations_before["PreferredLeaderElectionGoal"] == 2
        assert result.violations_after["PreferredLeaderElectionGoal"] == 0
        # every partition's leader is its lowest-index valid replica
        lead = np.asarray(final.partition_leader)
        rp = np.asarray(final.replica_partition)
        valid = np.asarray(final.replica_valid)
        for p in set(rp[valid].tolist()):
            rows = np.nonzero(valid & (rp == p))[0]
            assert lead[p] == rows.min()

    def test_dead_preferred_broker_tolerated(self):
        cluster = fixtures.homogeneous_cluster({0: "0", 1: "1", 2: "0"})
        cluster.create_replica(0, ("T1", 0), 0, False)
        cluster.create_replica(1, ("T1", 0), 1, True)
        cluster.set_replica_load(0, ("T1", 0), fixtures.load(1, 10, 10, 100))
        cluster.set_replica_load(1, ("T1", 0), fixtures.load(1, 10, 10, 100))
        state, maps = cluster.to_arrays(**PAD)
        import jax.numpy as jnp

        state = state.replace(broker_alive=state.broker_alive.at[0].set(False))
        ctx = ctx_for(state)
        opt = GoalOptimizer(goal_ids=(G.PREFERRED_LEADER_ELECTION,))
        final, result = opt.optimize(state, ctx, maps=maps)
        # the offline pre-phase relocates the head off the dead broker first;
        # the goal then (correctly) elects it — leadership never sits on broker 0
        assert result.violations_after["PreferredLeaderElectionGoal"] == 0
        lead_row = int(np.asarray(final.partition_leader)[0])
        assert int(np.asarray(final.replica_broker)[lead_row]) != 0


class TestRackAwareDistribution:
    def test_overloaded_rack_spreads_to_fair_share(self):
        # racks: 0 has brokers 0,1,2; rack 1 has brokers 3,4.  RF3 all in rack 0
        cluster = fixtures.homogeneous_cluster(
            {0: "0", 1: "0", 2: "0", 3: "1", 4: "1"}
        )
        for b in (0, 1, 2):
            cluster.create_replica(b, ("T1", 0), b, b == 0)
            cluster.set_replica_load(b, ("T1", 0), fixtures.load(1, 10, 10, 100))
        state, maps = cluster.to_arrays(**PAD)
        ctx = ctx_for(state)
        opt = GoalOptimizer(goal_ids=(G.RACK_AWARE_DISTRIBUTION,))
        final, result = opt.optimize(state, ctx, maps=maps)
        assert result.violations_before["RackAwareDistributionGoal"] == 1
        assert result.violations_after["RackAwareDistributionGoal"] == 0
        racks = np.asarray(final.broker_rack)[np.asarray(final.replica_broker)]
        valid = np.asarray(final.replica_valid)
        counts = np.bincount(racks[valid], minlength=2)
        assert counts.max() <= 2  # fair share = ceil(3/2)


class TestBrokerSetAware:
    def test_replica_moves_into_its_topic_broker_set(self):
        cluster = fixtures.homogeneous_cluster({0: "0", 1: "1", 2: "0", 3: "1"})
        cluster.create_replica(0, ("T1", 0), 0, True)    # T1 belongs to set 1!
        cluster.set_replica_load(0, ("T1", 0), fixtures.load(1, 10, 10, 100))
        cluster.create_replica(2, ("T2", 0), 0, True)    # T2 belongs to set 0
        cluster.set_replica_load(2, ("T2", 0), fixtures.load(1, 10, 10, 100))
        state, maps = cluster.to_arrays(**PAD)
        t1 = maps.topic_index["T1"]
        t2 = maps.topic_index["T2"]
        set_of_topic = [0] * state.num_topics
        set_of_topic[t1] = 1
        set_of_topic[t2] = 0
        ctx = ctx_for(
            state,
            broker_set_of_broker=[0, 1, 0, 1],
            broker_set_of_topic=set_of_topic,
        )
        opt = GoalOptimizer(goal_ids=(G.BROKER_SET_AWARE,))
        final, result = opt.optimize(state, ctx, maps=maps)
        assert result.violations_before["BrokerSetAwareGoal"] == 1
        assert result.violations_after["BrokerSetAwareGoal"] == 0
        rb = np.asarray(final.replica_broker)
        rp = np.asarray(final.replica_partition)
        valid = np.asarray(final.replica_valid)
        t1_rows = np.nonzero(valid & (rp == maps.partition_index[("T1", 0)]))[0]
        assert all(rb[r] in (1, 3) for r in t1_rows)


class TestTopicLeaderDistribution:
    def test_topic_leaders_spread_across_brokers(self):
        cluster = fixtures.homogeneous_cluster({0: "0", 1: "1", 2: "0"})
        # 6 partitions of T1; all leaders on broker 0 with followers elsewhere
        for i in range(6):
            cluster.create_replica(0, ("T1", i), 0, True)
            cluster.create_replica(1 + i % 2, ("T1", i), 1, False)
            cluster.set_replica_load(0, ("T1", i), fixtures.load(1, 10, 10, 100))
            cluster.set_replica_load(1 + i % 2, ("T1", i), fixtures.load(1, 10, 0, 100))
        from cruise_control_tpu.analyzer.constraint import BalancingConstraint

        state, maps = cluster.to_arrays(pad_replicas_to=16, pad_partitions_to=8, pad_topics_to=2)
        # the default 3.0 threshold tolerates this tiny fixture; tighten it so
        # six leaders on one broker actually violate the band
        constraint = BalancingConstraint.default(
            topic_replica_balance_threshold=1.1, topic_replica_balance_min_gap=1
        )
        ctx = ctx_for(state, constraint=constraint)
        opt = GoalOptimizer(
            goal_ids=(G.TOPIC_LEADER_DIST,), enable_heavy_goals=True
        )
        final, result = opt.optimize(state, ctx, maps=maps)
        assert result.violations_after["TopicLeaderReplicaDistributionGoal"] \
            <= result.violations_before["TopicLeaderReplicaDistributionGoal"]
        # leader spread must improve: broker 0 no longer owns all six
        lead = np.asarray(A.is_leader(final))
        rb = np.asarray(final.replica_broker)
        valid = np.asarray(final.replica_valid)
        on_b0 = (lead & valid & (rb == 0)).sum()
        assert on_b0 < 6


class TestKafkaAssignerMode:
    def test_rack_and_disk_compat_goals_run(self):
        cluster = fixtures.rack_aware_satisfiable()
        state, maps = cluster.to_arrays(pad_replicas_to=8, pad_partitions_to=8, pad_topics_to=2)
        ctx = ctx_for(state)
        opt = GoalOptimizer(
            goal_ids=(G.KAFKA_ASSIGNER_RACK, G.KAFKA_ASSIGNER_DISK),
            hard_ids=(G.KAFKA_ASSIGNER_RACK,),
        )
        final, result = opt.optimize(state, ctx, maps=maps)
        assert result.violations_after["KafkaAssignerEvenRackAwareGoal"] == 0
        racks = np.asarray(final.broker_rack)[np.asarray(final.replica_broker)]
        valid = np.asarray(final.replica_valid)
        rp = np.asarray(final.replica_partition)
        for p in set(rp[valid].tolist()):
            rs = racks[valid & (rp == p)]
            assert len(set(rs.tolist())) == len(rs)


class TestFastMode:
    @pytest.mark.slow
    def test_fast_mode_caps_rounds(self):
        """OptimizationOptions.fastMode: bounded wall-clock — every phase stops
        within FAST_MODE_MAX_ROUNDS rounds (fast.mode.per.broker.move.timeout.ms
        analogue)."""
        from cruise_control_tpu.analyzer.optimizer import FAST_MODE_MAX_ROUNDS
        from cruise_control_tpu.synthetic import SyntheticSpec, generate

        spec = SyntheticSpec(
            num_racks=4, num_brokers=12, num_topics=8, num_partitions=300,
            replication_factor=3, skew_brokers=4, seed=9,
            mean_disk=0.2, mean_nw_in=0.15,
        )
        state, maps = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers, fast_mode=True)
        opt = GoalOptimizer(enable_heavy_goals=True)
        final, result = opt.optimize(state, ctx)
        for r in result.goal_reports:
            # rounds accumulates over a goal's round types; each type is capped
            assert r.rounds <= FAST_MODE_MAX_ROUNDS * 4


class TestSourceCapping:
    @pytest.mark.slow
    def test_capped_rounds_reach_the_same_fixpoint(self):
        """max_active_brokers bounds per-round matrices; the while-loop still
        converges to zero hard violations, just over more rounds."""
        import jax

        from cruise_control_tpu.synthetic import SyntheticSpec, generate

        # Dropping the previously-compiled phase executables before this
        # test's fresh compile burst avoids a reproducible XLA:CPU LLVM
        # segfault on this machine (compile of the source-capped phase
        # variants crashes when the fast-mode variants are still resident;
        # clean process → passes).  Same class of CPU-backend fragility as
        # the AOT-cache SIGILL noted in conftest.py.
        jax.clear_caches()

        spec = SyntheticSpec(
            num_racks=4, num_brokers=16, num_topics=8, num_partitions=400,
            replication_factor=3, skew_brokers=4, seed=21,
            mean_disk=0.2, mean_nw_in=0.15,
        )
        state, maps = generate(spec)
        ctx = GoalContext.build(
            state.num_topics, state.num_brokers, max_active_brokers=4
        )
        opt = GoalOptimizer(enable_heavy_goals=True)
        final, result = opt.optimize(state, ctx)
        assert not result.violated_hard_goals, result.violations_after
        ctx_full = GoalContext.build(state.num_topics, state.num_brokers)
        _, result_full = opt.optimize(state, ctx_full)
        assert not result_full.violated_hard_goals

    def test_cap_window_rotates_over_all_active_brokers(self):
        """The capped source window must rotate with the round salt so a stuck
        top-M set cannot starve feasible brokers beyond the cap."""
        import jax.numpy as jnp
        import numpy as np

        from cruise_control_tpu.analyzer.proposers import _cap_sources

        need = jnp.asarray([0.0, 5.0, 4.0, 3.0, 2.0, 1.0, 0.5, 0.25], jnp.float32)
        ids, windows = _cap_sources(need, max_active=8)
        assert ids is None and int(windows) == 1  # no cap required

        # 7 active brokers, window of 3 → ceil(7/3) = 3 windows
        seen = set()
        for salt in range(3):
            ids, windows = _cap_sources(need, 3, jnp.int32(salt))
            ids = np.asarray(ids)
            assert ids.shape == (3,)
            assert int(windows) == 3
            seen.update(int(i) for i in ids)
        active = {1, 2, 3, 4, 5, 6, 7}
        assert active <= seen, f"rotation missed active brokers: {active - seen}"
        # salt 0 serves the neediest window first
        ids0, _ = _cap_sources(need, 3, jnp.int32(0))
        assert set(np.asarray(ids0)) == {1, 2, 3}

    def test_restricted_dst_matrices_match_full(self):
        """move_dst_matrix/_partition_occupancy with dst_brokers must equal the
        corresponding columns of the full [S, B] matrices (the capped fill path
        computes only the active window's columns)."""
        import jax.numpy as jnp

        from cruise_control_tpu.analyzer.acceptance import move_dst_matrix
        from cruise_control_tpu.analyzer.context import take_snapshot
        from cruise_control_tpu.analyzer.proposers import _partition_occupancy
        from cruise_control_tpu.synthetic import SyntheticSpec, generate

        spec = SyntheticSpec(
            num_racks=3, num_brokers=10, num_topics=4, num_partitions=60,
            replication_factor=3, skew_brokers=3, seed=5,
            mean_disk=0.2, mean_nw_in=0.15,
        )
        state, _ = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        snap = take_snapshot(state, ctx, enable_heavy=True)
        prior = jnp.ones(G.NUM_GOALS, bool)   # every goal's acceptance active
        cand = jnp.arange(12, dtype=jnp.int32) * 7 % state.num_replicas
        valid = np.asarray(state.replica_valid)[np.asarray(cand)]
        valid = jnp.asarray(valid)
        cols = jnp.asarray([8, 2, 5], jnp.int32)

        full = move_dst_matrix(state, ctx, snap, cand, valid, prior)
        sub = move_dst_matrix(state, ctx, snap, cand, valid, prior, dst_brokers=cols)
        np.testing.assert_array_equal(np.asarray(sub), np.asarray(full)[:, np.asarray(cols)])

        cand_part = state.replica_partition[cand]
        occ_full = _partition_occupancy(state, snap, cand_part, valid)
        occ_sub = _partition_occupancy(state, snap, cand_part, valid, dst_brokers=cols)
        np.testing.assert_array_equal(
            np.asarray(occ_sub), np.asarray(occ_full)[:, np.asarray(cols)]
        )
