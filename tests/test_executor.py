"""Executor tests against the fake backend.

Mirrors the reference's ``executor/ExecutorTest`` / ``ExecutionTaskPlannerTest`` tier
(SURVEY §4 tier 3): proposal → task planning, strategy ordering, 3-phase execution,
concurrency caps, throttling, and stop semantics.
"""

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.backend import FakeClusterBackend
from cruise_control_tpu.executor import (
    ConcurrencyConfig,
    ExecutionTaskPlanner,
    Executor,
    ExecutionConcurrencyManager,
    ExecutorNotifier,
    OngoingExecutionError,
    PrioritizeSmallReplicaMovementStrategy,
    StrategyContext,
    TaskState,
)


def make_backend(latency=1):
    backend = FakeClusterBackend(reassignment_latency_polls=latency)
    for b in range(4):
        backend.add_broker(b, rack=str(b % 2))
    for p in range(6):
        backend.create_partition(("T", p), [p % 4, (p + 1) % 4], load=[1.0, 10.0, 10.0, 100.0])
    return backend


def move_proposal(tp, old, new, size=100.0):
    return ExecutionProposal(
        tp=tp, partition_size=size, old_leader=old[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
    )


class TestPlanner:
    def test_split_and_order_by_strategy(self):
        p_small = move_proposal(("T", 0), [0, 1], [2, 1], size=10.0)
        p_big = move_proposal(("T", 1), [1, 2], [3, 2], size=500.0)
        p_lead = move_proposal(("T", 2), [2, 3], [3, 2])  # leadership only
        planner = ExecutionTaskPlanner([PrioritizeSmallReplicaMovementStrategy()])
        planner.add_proposals([p_big, p_small, p_lead])
        assert [t.proposal.tp for t in planner.inter_broker] == [("T", 0), ("T", 1)]
        # p_small/p_big also change the leader, so they plan a leadership task too
        assert {t.proposal.tp for t in planner.leadership} == {
            ("T", 0), ("T", 1), ("T", 2)
        }

    def test_concurrency_caps_respected(self):
        proposals = [
            move_proposal(("T", i), [0, 1], [2 + (i % 2), 1]) for i in range(6)
        ]
        planner = ExecutionTaskPlanner()
        planner.add_proposals(proposals)
        mgr = ExecutionConcurrencyManager(ConcurrencyConfig(per_broker_moves=2, cluster_moves=10))
        ready = planner.ready_inter_broker_tasks(mgr, in_flight=[])
        # every task touches broker 0 (remove) — per-broker cap of 2 binds
        assert len(ready) == 2


class TestExecution:
    def test_three_phase_execution_applies_to_backend(self):
        backend = make_backend()
        executor = Executor(backend, throttle_rate_bytes=1e6)
        proposals = [
            move_proposal(("T", 0), [0, 1], [2, 1]),
            move_proposal(("T", 1), [1, 2], [1, 3]),
            move_proposal(("T", 2), [2, 3], [3, 2]),  # leadership
        ]
        summary = executor.execute_proposals(proposals)
        assert summary.succeeded, vars(summary)
        topics = backend.describe_topics()
        by_tp = {i.tp: i for infos in topics.values() for i in infos}
        assert set(by_tp[("T", 0)].replicas) == {1, 2}
        assert set(by_tp[("T", 1)].replicas) == {1, 3}
        assert by_tp[("T", 2)].leader == 3
        # throttles set then cleared
        kinds = [k for k, _ in backend.admin_log]
        assert "throttle" in kinds and kinds[-1] != "throttle"
        assert backend.current_throttle is None

    def test_execution_pauses_and_resumes_sampling(self):
        backend = make_backend()
        events = []
        executor = Executor(
            backend,
            pause_sampling=lambda r: events.append(("pause", r)),
            resume_sampling=lambda r: events.append(("resume", r)),
        )
        executor.execute_proposals([move_proposal(("T", 0), [0, 1], [2, 1])])
        assert events[0][0] == "pause" and events[-1][0] == "resume"

    def test_reject_concurrent_execution(self):
        backend = make_backend(latency=50)
        executor = Executor(backend, progress_check_interval_s=0.01)
        executor.execute_proposals(
            [move_proposal(("T", 0), [0, 1], [2, 1])], wait=False
        )
        with pytest.raises(OngoingExecutionError):
            executor.execute_proposals([move_proposal(("T", 1), [1, 2], [1, 3])])
        executor.stop_execution()
        executor.await_completion()

    def test_stop_execution_aborts_pending(self):
        backend = make_backend(latency=100)
        executor = Executor(
            backend,
            concurrency=ConcurrencyConfig(per_broker_moves=1, cluster_moves=1),
            progress_check_interval_s=0.01,
        )
        proposals = [
            move_proposal(("T", i), [0, 1], [2 + (i % 2), 1]) for i in range(4)
        ]
        executor.execute_proposals(proposals, wait=False)
        import time

        time.sleep(0.05)
        executor.stop_execution()
        summary = executor.await_completion(timeout_s=30)
        assert summary is not None and summary.stopped

    def test_stop_execution_on_idle_executor_is_noop(self):
        """Regression: stop on an idle executor used to pin the state to
        STOPPING_EXECUTION forever with nothing to stop."""
        backend = make_backend()
        executor = Executor(backend)
        executor.stop_execution()
        assert executor.state == "NO_TASK_IN_PROGRESS"
        # and a fresh execution still starts normally afterwards
        summary = executor.execute_proposals([move_proposal(("T", 0), [0, 1], [2, 1])])
        assert summary.succeeded
        assert not summary.stopped
        executor.stop_execution()   # after completion: also a no-op
        assert executor.state == "NO_TASK_IN_PROGRESS"

    def test_lost_task_accounting_on_thread_unwind(self):
        """Regression: tasks still IN_PROGRESS when the execution thread
        unwinds used to land in no bucket; they must be counted as failed so
        completed + dead + aborted + failed == total."""

        class ExplodingBackend(FakeClusterBackend):
            def list_partition_reassignments(self):
                raise ValueError("metadata fetch exploded")

        backend = ExplodingBackend()
        for b in range(4):
            backend.add_broker(b, rack=str(b % 2))
        for p in range(3):
            backend.create_partition(("T", p), [p % 4, (p + 1) % 4], load=[1.0] * 4)
        executor = Executor(backend, progress_check_interval_s=0.01)
        proposals = [move_proposal(("T", 0), [0, 1], [2, 1])]
        summary = executor.execute_proposals(proposals)
        assert summary.error is not None and "ValueError" in summary.error
        assert not summary.succeeded
        tasks = executor._planner.all_tasks
        assert summary.total == len(tasks)
        assert summary.failed >= 1      # the in-flight move when the error hit
        # executor is reusable after the degraded run
        assert executor.state == "NO_TASK_IN_PROGRESS"
        assert not executor.has_ongoing_execution

    def test_cleanup_steps_run_independently(self):
        """One failing cleanup step (resume callback) must not skip the rest:
        throttles still cleared, summary still produced, notifier still told."""
        backend = make_backend()
        finished = []

        class Note(ExecutorNotifier):
            def on_execution_finished(self, summary):
                finished.append(summary)

        def bad_resume(reason):
            raise RuntimeError("monitor is gone")

        executor = Executor(
            backend,
            throttle_rate_bytes=1e6,
            notifier=Note(),
            pause_sampling=lambda r: None,
            resume_sampling=bad_resume,
        )
        summary = executor.execute_proposals([move_proposal(("T", 0), [0, 1], [2, 1])])
        assert summary.succeeded
        assert backend.current_throttle is None
        assert finished == [summary]

    def test_dead_destination_marks_task_dead(self):
        backend = make_backend(latency=3)
        executor = Executor(backend, progress_check_interval_s=0.01)
        import threading, time

        def killer():
            time.sleep(0.015)
            backend.kill_broker(2)

        t = threading.Thread(target=killer)
        t.start()
        # leader stays 0, so only a single replica-move task is planned
        summary = executor.execute_proposals([move_proposal(("T", 0), [0, 1], [0, 2])])
        t.join()
        # either it completed before the kill or it was marked dead — never hangs
        assert summary.completed + summary.dead == 1


class TestCombinedProposal:
    def test_replica_move_with_leadership_change(self):
        """A proposal carrying both a follower move AND a leadership transfer must
        apply both (planner emits one task per action)."""
        backend = make_backend()
        executor = Executor(backend)
        # (T,0): replicas [0,1] leader 0 -> replicas (2,0): 1 moves to 2, leader 2
        summary = executor.execute_proposals([move_proposal(("T", 0), [0, 1], [2, 0])])
        assert summary.succeeded
        by_tp = {i.tp: i for infos in backend.describe_topics().values() for i in infos}
        assert set(by_tp[("T", 0)].replicas) == {0, 2}
        assert by_tp[("T", 0)].leader == 2


class TestIntraBrokerExecution:
    def test_logdir_only_moves_execute_via_intra_phase(self):
        """A logdir-moves map with no matching placement proposal still plans and
        executes intra-broker tasks (Executor.intraBrokerMoveReplicas :1679)."""
        backend = FakeClusterBackend()
        backend.add_broker(0, rack="0", logdirs={"/d1": 1e6, "/d2": 1e6})
        backend.add_broker(1, rack="1", logdirs={"/d1": 1e6})
        backend.create_partition(("T", 0), [0, 1], load=[1.0, 10.0, 10.0, 100.0])
        executor = Executor(backend)
        summary = executor.execute_proposals(
            [], logdir_moves={(("T", 0), 0): "/d2"}
        )
        assert summary.completed >= 1
        assert ("logdir", (("T", 0), 0, "/d2")) in backend.admin_log
