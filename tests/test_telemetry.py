"""Telemetry-plane tests (ISSUE 5): exposition, profiler, correlated tracing.

Acceptance criteria covered here:

* ``GET /METRICS`` returns parser-valid Prometheus text covering every sensor
  registered during a rebalance + sweep session (``test_metrics_lint_*``:
  round-trips the live page through the strict exposition parser);
* a warm optimize with the profiler enabled adds zero dispatches and zero
  compile events — asserted from the obs flight record — while its trace
  carries flops/bytes/memory-watermark cost attrs;
* one ``X-Request-Id`` sent to POST REBALANCE links the user task, the
  optimize trace and the execution trace via ``GET /TRACES?parent_id=``.
"""

import re
import threading

import jax
import jax.numpy as jnp
import pytest

from cruise_control_tpu.core.sensors import REGISTRY, SensorRegistry
from cruise_control_tpu.obs.exporter import (
    ExpositionError,
    parse_exposition,
    render_prometheus,
)
from cruise_control_tpu.obs.profiler import PROFILER, DeviceProfiler, profile_jit
from cruise_control_tpu.obs.recorder import (
    RECORDER,
    FlightRecorder,
    TraceRecord,
    current_parent_id,
    parent_scope,
)


# -- exposition renderer -------------------------------------------------------------


def _unescape_label(value: str) -> str:
    """Reverse of the exporter's label escaping (the parser keeps values in
    their on-the-wire escaped form)."""
    return re.sub(
        r'\\(n|"|\\)',
        lambda m: {"n": "\n", '"': '"', "\\": "\\"}[m.group(1)],
        value,
    )


class TestExporterRender:
    def _registry(self):
        reg = SensorRegistry()
        reg.timer("GoalOptimizer.proposal-computation-timer").update(0.5)
        reg.gauge("AnomalyDetector.balancedness-score").set(98.5)
        reg.counter("Executor.execution-started").inc(3)
        reg.meter("AnomalyDetector.anomaly-rate").mark(2)
        return reg

    def test_round_trips_through_strict_parser(self):
        text = render_prometheus(
            registry=self._registry(),
            recorder=FlightRecorder(),
            profiler=DeviceProfiler(),
        )
        parsed = parse_exposition(text)
        assert "cruise_control_tpu_timer_seconds" in parsed
        assert "cruise_control_tpu_counter_total" in parsed
        assert parsed["cruise_control_tpu_timer_seconds"]["type"] == "gauge"

    def test_dot_families_become_labels(self):
        text = render_prometheus(
            registry=self._registry(),
            recorder=FlightRecorder(),
            profiler=DeviceProfiler(),
        )
        parsed = parse_exposition(text)
        samples = parsed["cruise_control_tpu_counter_total"]["samples"]
        labelsets = [dict(labels) for labels, _ in samples]
        assert {"family": "Executor", "sensor": "execution-started"} in labelsets

    def test_timer_stats_complete(self):
        text = render_prometheus(
            registry=self._registry(),
            recorder=FlightRecorder(),
            profiler=DeviceProfiler(),
        )
        parsed = parse_exposition(text)
        stats = {
            dict(labels)["stat"]
            for labels, _ in parsed["cruise_control_tpu_timer_seconds"]["samples"]
        }
        assert stats == {"mean", "max", "last", "p50", "p95", "p99"}

    def test_label_escaping_survives_parse(self):
        reg = SensorRegistry()
        reg.counter('Weird.name-with"quote\\and\nnewline').inc()
        text = render_prometheus(
            registry=reg, recorder=FlightRecorder(), profiler=DeviceProfiler()
        )
        parsed = parse_exposition(text)   # must not raise
        samples = parsed["cruise_control_tpu_counter_total"]["samples"]
        assert len(samples) == 1

    @pytest.mark.parametrize("leaf", [
        "embedded\nnewline",
        'embedded"quote',
        "embedded\\backslash",
        "trailing-backslash\\",
    ])
    def test_each_escape_char_round_trips(self, leaf):
        # one edge case per escape the spec defines (\n, \", \\), plus the
        # nastiest composition: a value ENDING in backslash, which a sloppy
        # renderer turns into an escaped closing quote
        reg = SensorRegistry()
        reg.gauge(f"Edge.{leaf}").set(1.0)
        text = render_prometheus(
            registry=reg, recorder=FlightRecorder(), profiler=DeviceProfiler()
        )
        parsed = parse_exposition(text)
        samples = parsed["cruise_control_tpu_gauge"]["samples"]
        labels = dict(samples[0][0])
        assert _unescape_label(labels["sensor"]) == leaf
        assert _unescape_label(labels["family"]) == "Edge"

    def test_prefix_colliding_family_stays_a_label(self):
        # a sensor family named exactly like an exported metric family must
        # not forge new samples under that metric name: dotted families render
        # as LABEL VALUES, never as metric names, so the collision is inert
        reg = SensorRegistry()
        reg.counter("cruise_control_tpu_counter_total.requests").inc(2)
        reg.gauge("cruise_control_tpu_gauge.depth").set(7.0)
        text = render_prometheus(
            registry=reg, recorder=FlightRecorder(), profiler=DeviceProfiler()
        )
        parsed = parse_exposition(text)   # duplicate-series check must pass
        counters = parsed["cruise_control_tpu_counter_total"]["samples"]
        assert [(dict(ls), v) for ls, v in counters] == [(
            {"family": "cruise_control_tpu_counter_total",
             "sensor": "requests"}, 2.0,
        )]
        gauges = parsed["cruise_control_tpu_gauge"]["samples"]
        assert (dict(gauges[0][0]), gauges[0][1]) == (
            {"family": "cruise_control_tpu_gauge", "sensor": "depth"}, 7.0,
        )

    def test_flight_recorder_summary_rendered(self):
        rec = FlightRecorder(capacity=4)
        for i in range(6):
            rec.record(TraceRecord(
                kind="optimize", trace_id=f"t{i}", started_at=0.0,
                duration_s=0.1, platform="cpu",
            ))
        text = render_prometheus(
            registry=SensorRegistry(), recorder=rec, profiler=DeviceProfiler()
        )
        parsed = parse_exposition(text)
        ring = parsed["cruise_control_tpu_flight_ring_size"]["samples"]
        dropped = parsed["cruise_control_tpu_flight_dropped_total"]["samples"]
        assert ring[0][1] == 4.0
        assert dropped[0][1] == 2.0

    def test_profiler_totals_rendered(self):
        prof = DeviceProfiler()
        prof.on_call("optimizer.goal_step", ("k",), "sig", 0.01, [])
        prof.set_analysis(("k",), {"flops": 100.0, "bytes accessed": 200.0})
        prof.on_call("optimizer.goal_step", ("k",), "sig", 0.01, [])
        text = render_prometheus(
            registry=SensorRegistry(), recorder=FlightRecorder(), profiler=prof
        )
        parsed = parse_exposition(text)
        flops = parsed["cruise_control_tpu_executable_flops_total"]["samples"]
        assert dict(flops[0][0])["program"] == "optimizer.goal_step"
        assert flops[0][1] == 200.0   # 100 flops × 2 calls

    def test_gate_baseline_rendered(self):
        text = render_prometheus(
            registry=SensorRegistry(),
            recorder=FlightRecorder(),
            profiler=DeviceProfiler(),
        )
        parsed = parse_exposition(text)
        tiers = {
            dict(labels)["tier"]
            for labels, _ in parsed["cruise_control_tpu_gate_baseline"]["samples"]
        }
        assert {"config1", "config2_small", "mesh8"} <= tiers


# -- strict parser -------------------------------------------------------------------


VALID = (
    "# HELP m_a a counter\n"
    "# TYPE m_a counter\n"
    'm_a{x="1"} 2\n'
)


class TestExpositionParser:
    def test_valid_text_parses(self):
        parsed = parse_exposition(VALID)
        assert parsed["m_a"]["samples"] == [((("x", "1"),), 2.0)]

    def test_sample_without_type_rejected(self):
        with pytest.raises(ExpositionError, match="without preceding"):
            parse_exposition("# HELP m_a a\nm_a 1\n")

    def test_sample_without_help_rejected(self):
        with pytest.raises(ExpositionError, match="without preceding"):
            parse_exposition("# TYPE m_a counter\nm_a 1\n")

    def test_duplicate_series_rejected(self):
        with pytest.raises(ExpositionError, match="duplicate series"):
            parse_exposition(VALID + 'm_a{x="1"} 3\n')

    def test_distinct_labelsets_allowed(self):
        parse_exposition(VALID + 'm_a{x="2"} 3\n')

    def test_duplicate_type_rejected(self):
        with pytest.raises(ExpositionError, match="duplicate TYPE"):
            parse_exposition("# TYPE m_a counter\n" + VALID)

    def test_type_after_samples_rejected(self):
        with pytest.raises(ExpositionError, match="after its samples"):
            parse_exposition(VALID + "# TYPE m_a counter\n")

    def test_bad_metric_name_rejected(self):
        with pytest.raises(ExpositionError):
            parse_exposition(
                "# HELP 0bad x\n# TYPE 0bad counter\n0bad 1\n"
            )

    def test_bad_value_rejected(self):
        with pytest.raises(ExpositionError, match="invalid value"):
            parse_exposition(VALID.replace(" 2\n", " two\n"))

    def test_illegal_escape_rejected(self):
        bad = (
            "# HELP m_b b\n# TYPE m_b gauge\n"
            'm_b{x="a\\tb"} 1\n'          # \t is not a legal escape
        )
        with pytest.raises(ExpositionError, match="malformed"):
            parse_exposition(bad)

    def test_unknown_type_rejected(self):
        with pytest.raises(ExpositionError, match="unknown TYPE"):
            parse_exposition("# HELP m_c c\n# TYPE m_c widget\nm_c 1\n")

    def test_inf_nan_values_accepted(self):
        parse_exposition(
            "# HELP m_d d\n# TYPE m_d gauge\n"
            'm_d{s="a"} +Inf\nm_d{s="b"} -Inf\nm_d{s="c"} NaN\n'
        )


# -- device/executable profiler ------------------------------------------------------


class TestProfiler:
    def test_wrapper_registers_and_analyzes(self):
        prof_fn = profile_jit(
            "test.square", jax.jit(lambda x: (x * x).sum())
        )
        x = jnp.arange(64, dtype=jnp.float32)
        mark = PROFILER.mark()
        out = prof_fn(x)
        assert float(out) == float((x * x).sum())
        cost = PROFILER.cost_since(mark)
        assert cost["profiled_calls"] == 1
        assert cost["flops"] > 0
        assert "memory_peak_bytes" in cost
        totals = PROFILER.per_program_totals()
        assert totals["test.square"]["calls"] == 1
        assert totals["test.square"]["flops_total"] > 0

    def test_warm_calls_count_without_reanalysis(self):
        prof_fn = profile_jit("test.add", jax.jit(lambda x: x + 1))
        x = jnp.ones(8)
        prof_fn(x)
        entry_count = len(PROFILER.snapshot()["executables"])
        for _ in range(3):
            prof_fn(x)
        snap = PROFILER.snapshot()
        assert len(snap["executables"]) == entry_count   # no new signatures
        adds = [e for e in snap["executables"] if e["program"] == "test.add"]
        assert adds[0]["calls"] == 4

    def test_new_shape_is_new_signature(self):
        prof_fn = profile_jit("test.shapes", jax.jit(lambda x: x * 2))
        prof_fn(jnp.ones(4))
        prof_fn(jnp.ones(16))
        sigs = [
            e for e in PROFILER.snapshot()["executables"]
            if e["program"] == "test.shapes"
        ]
        assert len(sigs) == 2

    def test_disabled_profiler_is_transparent(self):
        prof = PROFILER.enabled
        try:
            PROFILER.enabled = False
            prof_fn = profile_jit("test.off", jax.jit(lambda x: x - 1))
            prof_fn(jnp.ones(4))
            assert not any(
                e["program"] == "test.off"
                for e in PROFILER.snapshot()["executables"]
            )
        finally:
            PROFILER.enabled = prof

    def test_memory_sampling_is_fallback_safe(self):
        samples = PROFILER.sample_memory()
        # CPU backends report no memory_stats — rows exist, values may be None
        for row in samples:
            assert "device" in row
            assert "bytes_in_use" in row


@pytest.mark.slow  # ~34 s class fixture (full warm optimize) on the 1-core box; nightly slow tier
class TestWarmOptimizeWithProfiler:
    """Acceptance: the profiler adds NOTHING to the warm path — dispatch
    count and compile events unchanged (PR 4 budget) — while the optimize
    trace carries the flops/bytes/memory cost block."""

    @pytest.fixture(scope="class")
    def warm_run(self):
        from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
        from cruise_control_tpu.analyzer import goals_base as G
        from tests.fixtures import service_test_goals, unbalanced2

        state, maps = unbalanced2().to_arrays()
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        goals = service_test_goals()
        opt = GoalOptimizer(
            goal_ids=goals,
            hard_ids=tuple(g for g in goals if g in G.HARD_GOALS),
            enable_heavy_goals=False,
        )
        assert PROFILER.enabled
        _, cold = opt.optimize(state, ctx)
        RECORDER.clear()
        _, warm = opt.optimize(state, ctx)
        trace = RECORDER.recent(1, kind="optimize")[0]
        return goals, cold, warm, trace

    def test_zero_extra_dispatches(self, warm_run):
        goals, cold, warm, trace = warm_run
        # the fused-dispatch budget: violations + 2 offline pre-phases +
        # one per goal + trailing violations = #goals + 4
        assert warm.num_dispatches == len(goals) + 4
        assert warm.num_dispatches == cold.num_dispatches
        assert trace.total_dispatches == warm.num_dispatches

    def test_zero_compile_events_warm(self, warm_run):
        _, _, _, trace = warm_run
        assert trace.compile_events == []

    def test_cost_attrs_on_trace(self, warm_run):
        _, _, _, trace = warm_run
        cost = trace.attrs["cost"]
        assert cost["flops"] > 0
        assert cost["bytes_accessed"] > 0
        assert cost["profiled_calls"] >= trace.total_dispatches - 1
        assert "memory_peak_bytes" in cost

    def test_profiler_surfaces_optimizer_programs(self, warm_run):
        programs = set(PROFILER.per_program_totals())
        assert "optimizer.goal_step" in programs
        assert "optimizer.phase" in programs
        assert "optimizer.violations" in programs


# -- request-correlated tracing ------------------------------------------------------


class TestParentScope:
    def test_scope_sets_and_restores(self):
        assert current_parent_id() is None
        with parent_scope("req-1"):
            assert current_parent_id() == "req-1"
            with parent_scope("req-2"):
                assert current_parent_id() == "req-2"
            assert current_parent_id() == "req-1"
        assert current_parent_id() is None

    def test_start_trace_inherits_scope(self):
        from cruise_control_tpu.obs import recorder as obs

        with parent_scope("req-xyz"):
            token = obs.start_trace("detector")
        trace = obs.finish_trace(token)
        assert trace.parent_id == "req-xyz"

    def test_recent_filters_by_parent_and_trace_id(self):
        rec = FlightRecorder()
        rec.record(TraceRecord(
            kind="optimize", trace_id="a", started_at=0, duration_s=0,
            platform="cpu", parent_id="p1",
        ))
        rec.record(TraceRecord(
            kind="execution", trace_id="b", started_at=0, duration_s=0,
            platform="cpu", parent_id="p1",
        ))
        rec.record(TraceRecord(
            kind="optimize", trace_id="c", started_at=0, duration_s=0,
            platform="cpu",
        ))
        assert {t.trace_id for t in rec.recent(10, parent_id="p1")} == {"a", "b"}
        assert [t.trace_id for t in rec.recent(10, trace_id="c")] == ["c"]
        assert rec.recent(10, kind="optimize", parent_id="p1")[0].trace_id == "a"

    def test_parent_id_round_trips_jsonl(self, tmp_path):
        from cruise_control_tpu.obs.recorder import read_jsonl

        path = str(tmp_path / "f.jsonl")
        rec = FlightRecorder(jsonl_path=path)
        rec.record(TraceRecord(
            kind="optimize", trace_id="a", started_at=0, duration_s=0,
            platform="cpu", parent_id="p9",
        ))
        assert read_jsonl(path)[0].parent_id == "p9"


# -- the served app: /METRICS + correlation over real HTTP ---------------------------


@pytest.fixture(scope="module")
def served():
    from cruise_control_tpu.api.server import make_server
    from cruise_control_tpu.client import CruiseControlClient
    from tests.test_api import build_app

    app = build_app()
    server = make_server(app, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = CruiseControlClient(
        f"http://127.0.0.1:{server.server_address[1]}", poll_timeout_s=600.0
    )
    yield app, client
    server.shutdown()


@pytest.mark.usefixtures("served")
class TestServedTelemetry:
    # ~33 s on the 1-core box (real HTTP rebalance = full optimize); nightly
    # slow tier — the schema/lint/propagation units below stay fast
    @pytest.mark.slow
    def test_request_id_walks_task_optimize_execution(self, served):
        """Acceptance: ONE X-Request-Id sent to POST REBALANCE retrieves the
        user task, the optimize trace and the execution trace."""
        app, client = served
        rid = "walk-me-7f3a"
        out = client.rebalance(dryrun=False, wait=True, request_id=rid)
        assert out is not None
        body = client.traces(parent_id=rid, limit=50)
        kinds = {t["kind"] for t in body["traces"]}
        assert {"user_task", "optimize", "execution"} <= kinds, kinds
        for t in body["traces"]:
            assert t["parent_id"] == rid
        # the user task also reports the id
        tasks = client.user_tasks()["userTasks"]
        assert any(t.get("RequestId") == rid for t in tasks)

    def test_generated_request_id_echoed(self, served):
        _, client = served
        status, _, headers = client._request("GET", "state")
        assert status == 200
        assert headers.get("X-Request-Id", "").startswith("req-")

    def test_metrics_lint_full_session_coverage(self, served):
        """Acceptance + CI metrics-lint: after a rebalance + sweep session the
        /METRICS page is strictly parser-valid and covers EVERY registered
        sensor (timers, gauges, counters, meters)."""
        app, client = served
        client.simulate(add_broker_counts=[0, 1], load_factors=[1.0, 1.25])
        text = client.metrics()
        parsed = parse_exposition(text)

        by_family = {
            "timers": "cruise_control_tpu_timer_count",
            "gauges": "cruise_control_tpu_gauge",
            "counters": "cruise_control_tpu_counter_total",
            "meters": "cruise_control_tpu_meter_total",
        }
        snap = REGISTRY.snapshot()
        for kind, metric in by_family.items():
            exported = {
                (dict(labels)["family"], dict(labels)["sensor"])
                for labels, _ in parsed.get(metric, {"samples": []})["samples"]
            }
            for name in snap.get(kind, {}):
                fam, _, leaf = name.partition(".")
                key = (fam, leaf) if leaf else ("", fam)
                assert key in exported, f"{kind} sensor {name} missing from page"
        # the session's signature sensors all made it
        counters = {
            dict(labels)["sensor"]
            for labels, _ in parsed["cruise_control_tpu_counter_total"]["samples"]
        }
        assert "sweeps" in counters            # ScenarioPlanner.sweeps
        assert "traces-recorded" in counters   # FlightRecorder
        # profiled executables + scrape self-metrics are on the page
        assert "cruise_control_tpu_executable_calls_total" in parsed
        timers = {
            dict(labels)["sensor"]
            for labels, _ in parsed["cruise_control_tpu_timer_count"]["samples"]
        }
        assert "render-timer" in timers        # MetricsExporter.render-timer

    def test_metrics_content_type_plain_text(self, served):
        app, client = served
        import urllib.request

        url = f"{client.base_url}/kafkacruisecontrol/metrics"
        with urllib.request.urlopen(url) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            assert b"# TYPE cruise_control_tpu_" in resp.read()

    def test_state_carries_profiler_block(self, served):
        from cruise_control_tpu.api.schemas import validate_endpoint

        app, client = served
        body = client.state()
        validate_endpoint("STATE", body)
        assert body["Profiler"]["enabled"] is True
        assert isinstance(body["Profiler"]["executables"], list)

    def test_traces_endpoint_schema_with_parent(self, served):
        from cruise_control_tpu.api.schemas import validate_endpoint

        app, client = served
        body = client.traces(limit=10)
        validate_endpoint("TRACES", body)
