"""Multi-device sharding tests (8 virtual CPU devices, see conftest).

Two layers of evidence that the scale-out solver is semantics-preserving:

* the explicit shard_map collectives in ``parallel.sharded`` agree with their
  single-device counterparts element-for-element (including argmax tie-breaks);
* the full ``ShardedGoalOptimizer`` produces **identical proposals** to the
  single-device ``GoalOptimizer`` on the same cluster — sharding is an
  execution detail, not a semantics change (the invariant the reference gets
  trivially from being single-JVM, SURVEY §2.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
from cruise_control_tpu.analyzer.context import segment_argmax
from cruise_control_tpu.parallel import (
    ShardedGoalOptimizer,
    pad_replicas,
    shard_state,
    solver_mesh,
)
from cruise_control_tpu.parallel.sharded import (
    sharded_gather,
    sharded_scatter_set,
    sharded_segment_argmax,
    sharded_segment_sum,
)
from cruise_control_tpu.synthetic import SyntheticSpec, generate

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 virtual devices"
    return solver_mesh(jax.devices()[:N_DEV])


class TestShardedPrimitives:
    R, B = 512, 16

    def _data(self, seed=0):
        rng = np.random.default_rng(seed)
        vals = jnp.asarray(rng.normal(size=self.R).astype(np.float32))
        seg = jnp.asarray(rng.integers(0, self.B, size=self.R, dtype=np.int32))
        elig = jnp.asarray(rng.random(self.R) < 0.7)
        return vals, seg, elig

    def test_segment_sum_matches(self, mesh):
        vals, seg, _ = self._data()
        want = jax.ops.segment_sum(vals, seg, num_segments=self.B)
        got = sharded_segment_sum(mesh, vals, seg, self.B)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_segment_sum_2d(self, mesh):
        rng = np.random.default_rng(3)
        vals = jnp.asarray(rng.normal(size=(self.R, 4)).astype(np.float32))
        seg = jnp.asarray(rng.integers(0, self.B, size=self.R, dtype=np.int32))
        want = jax.ops.segment_sum(vals, seg, num_segments=self.B)
        got = sharded_segment_sum(mesh, vals, seg, self.B)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_segment_argmax_matches_including_ties(self, mesh):
        vals, seg, elig = self._data(7)
        # force score ties so the lowest-global-index rule is exercised
        vals = jnp.round(vals * 4) / 4
        want = segment_argmax(vals, seg, self.B, elig)
        got = sharded_segment_argmax(mesh, vals, seg, self.B, elig)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_gather_matches(self, mesh):
        vals, _, _ = self._data(11)
        ids = jnp.asarray([0, 5, 511, 128, -1, 64, 63, 65], jnp.int32)
        got = sharded_gather(mesh, vals, ids)
        want = jnp.where(ids >= 0, vals[jnp.maximum(ids, 0)], 0.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    def test_scatter_set_matches(self, mesh):
        vals, _, _ = self._data(13)
        ids = jnp.asarray([3, 200, 511, -1, 64], jnp.int32)
        upd = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0], jnp.float32)
        got = sharded_scatter_set(mesh, vals, ids, upd)
        want = vals.at[jnp.where(ids >= 0, ids, self.R)].set(upd, mode="drop")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


@pytest.mark.slow
class TestShardedSolver:
    def _cluster(self):
        spec = SyntheticSpec(
            num_racks=4,
            num_brokers=16,
            num_topics=8,
            num_partitions=512,          # 1536 replicas — divisible by 8
            replication_factor=3,
            distribution="exponential",
            skew_brokers=4,
            seed=17,
            mean_disk=0.2,
            mean_nw_in=0.15,
        )
        return generate(spec)

    def test_proposals_identical_to_single_device(self, mesh):
        state, maps = self._cluster()
        ctx = GoalContext.build(state.num_topics, state.num_brokers)

        single_final, single_res = GoalOptimizer(enable_heavy_goals=True).optimize(
            state, ctx, maps=maps
        )
        sharded_final, sharded_res = ShardedGoalOptimizer(
            mesh=mesh, enable_heavy_goals=True
        ).optimize(state, ctx, maps=maps)

        np.testing.assert_array_equal(
            np.asarray(single_final.replica_broker),
            np.asarray(sharded_final.replica_broker)[: state.num_replicas],
        )
        np.testing.assert_array_equal(
            np.asarray(single_final.partition_leader),
            np.asarray(sharded_final.partition_leader),
        )
        assert [
            (p.tp, p.old_replicas, p.new_replicas) for p in single_res.proposals
        ] == [(p.tp, p.old_replicas, p.new_replicas) for p in sharded_res.proposals]
        assert single_res.violations_after == sharded_res.violations_after

    def test_padding_preserves_semantics(self, mesh):
        state, maps = self._cluster()
        padded = pad_replicas(state, 7)  # deliberately awkward multiple
        assert padded.num_replicas % 7 == 0
        assert int(padded.replica_valid.sum()) == state.num_replicas

    def test_state_sharding_layout(self, mesh):
        state, _ = self._cluster()
        sharded = shard_state(state, mesh)
        # replica-axis arrays sharded over the mesh, broker arrays replicated
        r_shard = sharded.replica_broker.sharding
        assert r_shard.spec[0] == "replicas"
        b_shard = sharded.broker_capacity.sharding
        assert all(s is None for s in b_shard.spec) or b_shard.spec == ()
