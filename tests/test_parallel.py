"""Multi-device sharding tests (8 virtual CPU devices, see conftest).

Two layers of evidence that the scale-out solver is semantics-preserving:

* the explicit shard_map collectives in ``parallel.sharded`` agree with their
  single-device counterparts element-for-element (including argmax tie-breaks);
* the full ``ShardedGoalOptimizer`` produces **identical proposals** to the
  single-device ``GoalOptimizer`` on the same cluster — sharding is an
  execution detail, not a semantics change (the invariant the reference gets
  trivially from being single-JVM, SURVEY §2.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
from cruise_control_tpu.analyzer.context import segment_argmax
from cruise_control_tpu.parallel import (
    ShardedGoalOptimizer,
    pad_replicas,
    shard_state,
    solver_mesh,
)
from cruise_control_tpu.parallel.sharded import (
    sharded_gather,
    sharded_scatter_set,
    sharded_segment_argmax,
    sharded_segment_sum,
)
from cruise_control_tpu.synthetic import SyntheticSpec, generate

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 virtual devices"
    return solver_mesh(jax.devices()[:N_DEV])


class TestShardedPrimitives:
    R, B = 512, 16

    def _data(self, seed=0):
        rng = np.random.default_rng(seed)
        vals = jnp.asarray(rng.normal(size=self.R).astype(np.float32))
        seg = jnp.asarray(rng.integers(0, self.B, size=self.R, dtype=np.int32))
        elig = jnp.asarray(rng.random(self.R) < 0.7)
        return vals, seg, elig

    def test_segment_sum_matches(self, mesh):
        vals, seg, _ = self._data()
        want = jax.ops.segment_sum(vals, seg, num_segments=self.B)
        got = sharded_segment_sum(mesh, vals, seg, self.B)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_segment_sum_2d(self, mesh):
        rng = np.random.default_rng(3)
        vals = jnp.asarray(rng.normal(size=(self.R, 4)).astype(np.float32))
        seg = jnp.asarray(rng.integers(0, self.B, size=self.R, dtype=np.int32))
        want = jax.ops.segment_sum(vals, seg, num_segments=self.B)
        got = sharded_segment_sum(mesh, vals, seg, self.B)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_segment_argmax_matches_including_ties(self, mesh):
        vals, seg, elig = self._data(7)
        # force score ties so the lowest-global-index rule is exercised
        vals = jnp.round(vals * 4) / 4
        want = segment_argmax(vals, seg, self.B, elig)
        got = sharded_segment_argmax(mesh, vals, seg, self.B, elig)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_gather_matches(self, mesh):
        vals, _, _ = self._data(11)
        ids = jnp.asarray([0, 5, 511, 128, -1, 64, 63, 65], jnp.int32)
        got = sharded_gather(mesh, vals, ids)
        want = jnp.where(ids >= 0, vals[jnp.maximum(ids, 0)], 0.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    def test_scatter_set_matches(self, mesh):
        vals, _, _ = self._data(13)
        ids = jnp.asarray([3, 200, 511, -1, 64], jnp.int32)
        upd = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0], jnp.float32)
        got = sharded_scatter_set(mesh, vals, ids, upd)
        want = vals.at[jnp.where(ids >= 0, ids, self.R)].set(upd, mode="drop")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


@pytest.mark.slow
class TestShardedSolver:
    def _cluster(self):
        spec = SyntheticSpec(
            num_racks=4,
            num_brokers=16,
            num_topics=8,
            num_partitions=512,          # 1536 replicas — divisible by 8
            replication_factor=3,
            distribution="exponential",
            skew_brokers=4,
            seed=17,
            mean_disk=0.2,
            mean_nw_in=0.15,
        )
        return generate(spec)

    def test_proposals_identical_to_single_device(self, mesh):
        state, maps = self._cluster()
        ctx = GoalContext.build(state.num_topics, state.num_brokers)

        single_final, single_res = GoalOptimizer(enable_heavy_goals=True).optimize(
            state, ctx, maps=maps
        )
        sharded_final, sharded_res = ShardedGoalOptimizer(
            mesh=mesh, enable_heavy_goals=True
        ).optimize(state, ctx, maps=maps)

        np.testing.assert_array_equal(
            np.asarray(single_final.replica_broker),
            np.asarray(sharded_final.replica_broker)[: state.num_replicas],
        )
        np.testing.assert_array_equal(
            np.asarray(single_final.partition_leader),
            np.asarray(sharded_final.partition_leader),
        )
        assert [
            (p.tp, p.old_replicas, p.new_replicas) for p in single_res.proposals
        ] == [(p.tp, p.old_replicas, p.new_replicas) for p in sharded_res.proposals]
        assert single_res.violations_after == sharded_res.violations_after

    def test_padding_preserves_semantics(self, mesh):
        state, maps = self._cluster()
        padded = pad_replicas(state, 7)  # deliberately awkward multiple
        assert padded.num_replicas % 7 == 0
        assert int(padded.replica_valid.sum()) == state.num_replicas

    def test_state_sharding_layout(self, mesh):
        state, _ = self._cluster()
        sharded = shard_state(state, mesh)
        # replica-axis arrays sharded over the mesh, broker arrays replicated
        r_shard = sharded.replica_broker.sharding
        assert r_shard.spec[0] == "replicas"
        b_shard = sharded.broker_capacity.sharding
        assert all(s is None for s in b_shard.spec) or b_shard.spec == ()


# -- ISSUE 14: the O(1)-collective shard_map solver path ----------------------------


@pytest.mark.slow  # ~65 s on the 1-core box; CI's sharded-tier step runs this class BY NAME (no -m filter), so coverage stays on every push
class TestSpmdSolverEquivalence:
    """The shard_map fast path is semantics-free: placements, proposals and
    violations equal the single-device solver bit-for-bit — including shapes
    whose replica count does NOT divide the mesh (the shard-padding edge)."""

    def _cluster(self, partitions=509, rf=3, brokers=12):
        # 509 × 3 = 1527 replicas: NOT a multiple of 8 — exercises pad_replicas
        spec = SyntheticSpec(
            num_racks=4, num_brokers=brokers, num_topics=6,
            num_partitions=partitions, replication_factor=rf,
            distribution="exponential", skew_brokers=3, seed=23,
            mean_disk=0.2, mean_nw_in=0.15,
        )
        return generate(spec)

    def _goals(self):
        from cruise_control_tpu.analyzer import goals_base as G

        return (G.RACK_AWARE, G.REPLICA_CAPACITY, G.DISK_CAPACITY,
                G.REPLICA_DISTRIBUTION)

    def test_uneven_replica_count_bit_identical(self, mesh):
        from cruise_control_tpu.analyzer import goals_base as G

        state, maps = self._cluster()
        assert (state.num_replicas % N_DEV) != 0, "fixture must hit the pad edge"
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        goals = self._goals()
        kw = dict(goal_ids=goals,
                  hard_ids=tuple(g for g in goals if g in G.HARD_GOALS),
                  enable_heavy_goals=False)
        sf, sres = GoalOptimizer(**kw).optimize(state, ctx, maps=maps)
        sh = ShardedGoalOptimizer(mesh=mesh, **kw)
        assert sh.use_spmd
        shf, shres = sh.optimize(state, ctx, maps=maps)
        np.testing.assert_array_equal(
            np.asarray(sf.replica_broker),
            np.asarray(shf.replica_broker)[: state.num_replicas],
        )
        np.testing.assert_array_equal(
            np.asarray(sf.partition_leader), np.asarray(shf.partition_leader)
        )
        assert [
            (p.tp, p.old_replicas, p.new_replicas) for p in sres.proposals
        ] == [(p.tp, p.old_replicas, p.new_replicas) for p in shres.proposals]
        assert sres.violations_after == shres.violations_after
        assert sres.balancedness_score == shres.balancedness_score

    def test_gspmd_fallback_for_unsupported_goals(self, mesh, monkeypatch):
        """Goal lists with PreferredLeaderElectionGoal route to the legacy
        GSPMD path (use_spmd False) and still match single-device."""
        from cruise_control_tpu.analyzer import goals_base as G

        state, _ = self._cluster(partitions=128, brokers=8)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        goals = (G.RACK_AWARE, G.REPLICA_CAPACITY, G.PREFERRED_LEADER_ELECTION)
        kw = dict(goal_ids=goals,
                  hard_ids=(G.RACK_AWARE, G.REPLICA_CAPACITY),
                  enable_heavy_goals=False)
        sh = ShardedGoalOptimizer(mesh=mesh, **kw)
        assert not sh.use_spmd
        _, sres = GoalOptimizer(**kw).optimize(state, ctx)
        _, shres = sh.optimize(state, ctx)
        assert sres.total_moves == shres.total_moves
        assert sres.violations_after == shres.violations_after

    def test_spmd_env_kill_switch(self, mesh, monkeypatch):
        monkeypatch.setenv("CC_TPU_SHARDED_SPMD", "0")
        sh = ShardedGoalOptimizer(mesh=mesh, enable_heavy_goals=False)
        assert not sh.use_spmd


class TestShardedSwapApply:
    """Regression: a kept swap whose endpoint is owned by a LOWER-index shard
    produces a NEGATIVE local scatter index after the offset shift — under
    ``mode="drop"`` a negative index WRAPS (only >= n drops), so the unguarded
    apply corrupted an unrelated local replica's broker/disk on every shard
    above the owner.  The sharded apply must equal the single-device
    ``swap_replicas`` bit-for-bit for cross-shard endpoint pairs."""

    def test_cross_shard_swap_matches_single_device(self, mesh):
        from functools import partial

        from cruise_control_tpu.analyzer.moves import (
            KIND_SWAP,
            MoveBatch,
            apply_moves,
        )
        from cruise_control_tpu.model import arrays as A
        from cruise_control_tpu.parallel.mesh import REPLICA_AXIS, replicate
        from cruise_control_tpu.parallel.solver import _state_specs
        from cruise_control_tpu.parallel.spmd import ReplicaRows, SpmdInfo
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = SyntheticSpec(
            num_racks=2, num_brokers=8, num_topics=2, num_partitions=32,
            replication_factor=2, distribution="uniform", skew_brokers=0,
            seed=41,
        )
        state, _ = generate(spec)
        assert state.num_replicas % N_DEV == 0
        # endpoints on the FIRST and LAST shard: every shard in between (and
        # the last one, for the first id) sees a negative local index
        a = jnp.int32(3)
        b = jnp.int32(state.num_replicas - 2)
        rows = ReplicaRows(
            partition=state.replica_partition[jnp.stack([a, b])],
            broker=state.replica_broker[jnp.stack([a, b])],
            disk=state.replica_disk[jnp.stack([a, b])],
            valid=jnp.ones(2, bool),
            is_leader=jnp.zeros(2, bool),
            base_load=state.base_load[jnp.stack([a, b])],
            eff_load=state.base_load[jnp.stack([a, b])],
        )
        moves = MoveBatch(
            kind=jnp.asarray(KIND_SWAP, jnp.int32),
            replica=jnp.stack([a]),
            dst_broker=state.replica_broker[jnp.stack([b])],
            dst_replica=jnp.stack([b]),
            score=jnp.ones(1, jnp.float32),
            rows=rows,
            view_replica=jnp.zeros(1, jnp.int32),
            view_dst_replica=jnp.ones(1, jnp.int32),
        )
        keep = jnp.ones(1, bool)

        want = A.swap_replicas(state, jnp.stack([a]), jnp.stack([b]))

        sstate = shard_state(state, mesh)
        spmd = SpmdInfo(
            axis=REPLICA_AXIS, n=N_DEV, global_R=sstate.num_replicas
        )
        sspec = _state_specs(sstate)
        out = shard_map(
            partial(apply_moves, spmd=spmd),
            mesh=mesh,
            in_specs=(sspec, P(), P()),
            out_specs=sspec,
            check_rep=False,
        )(sstate, replicate(moves, mesh), replicate(keep, mesh))
        np.testing.assert_array_equal(
            np.asarray(out.replica_broker), np.asarray(want.replica_broker)
        )
        np.testing.assert_array_equal(
            np.asarray(out.replica_disk), np.asarray(want.replica_disk)
        )


@pytest.mark.slow  # ~23 s on the 1-core box; CI's sharded-tier step runs this class BY NAME (no -m filter), so coverage stays on every push
class TestCollectiveAccounting:
    """ISSUE 14 satellite: the 120-all-reduce GSPMD regression can't silently
    return — the sharded goal step's LOGICAL program must stay at a
    single-digit collective count, and a warm sharded solve must issue zero
    XLA recompiles."""

    #: the committed design budget: before/after snapshots (2×(psum+pmin)=4),
    #: per-round snapshot (psum+pmin=2), candidate-merge + destination-colmax
    #: all_gathers (2) and the occupancy/row-fetch psum (1) — 9 for the
    #: RackAware step (its violation sum rides the snapshot psum)
    MAX_COLLECTIVES = 9

    def _sharded(self, mesh):
        from cruise_control_tpu.parallel.mesh import REPLICA_AXIS, replicate
        from cruise_control_tpu.parallel.solver import sharded_steps
        from cruise_control_tpu.parallel.spmd import SpmdInfo

        spec = SyntheticSpec(
            num_racks=4, num_brokers=8, num_topics=4, num_partitions=256,
            replication_factor=3, distribution="exponential", skew_brokers=2,
            seed=29, mean_disk=0.2, mean_nw_in=0.15,
        )
        state, _ = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        sstate = shard_state(state, mesh)
        sctx = replicate(ctx, mesh)
        spmd = SpmdInfo(
            axis=REPLICA_AXIS, n=N_DEV, global_R=sstate.num_replicas
        )
        return state, ctx, sstate, sctx, sharded_steps(mesh, spmd)

    def test_goal_step_logical_collectives_single_digit(self, mesh):
        import re

        from cruise_control_tpu.analyzer import goals_base as G
        from cruise_control_tpu.analyzer.goal_rounds import GOAL_ROUNDS
        from cruise_control_tpu.parallel.spmd import LOGICAL_COLLECTIVE_RE

        _, _, sstate, sctx, steps = self._sharded(mesh)
        lowered = steps["goal_step"].lower(
            sstate, sctx,
            gid=G.RACK_AWARE, round_fns=GOAL_ROUNDS[G.RACK_AWARE],
            max_rounds=2000, enable_heavy=False,
            prior_ids=(), admit_ids=(G.RACK_AWARE,),
        )
        n = len(re.findall(LOGICAL_COLLECTIVE_RE, lowered.as_text()))
        assert 0 < n <= self.MAX_COLLECTIVES, (
            f"sharded goal step lowered with {n} collectives "
            f"(budget {self.MAX_COLLECTIVES}) — the per-reduction-site "
            "collective regression is back"
        )

    def test_warm_sharded_solve_zero_recompiles(self, mesh):
        from cruise_control_tpu.analyzer import goals_base as G
        from cruise_control_tpu.obs.recorder import RECORDER

        state, ctx, _, _, _ = self._sharded(mesh)
        goals = (G.RACK_AWARE, G.REPLICA_CAPACITY, G.DISK_CAPACITY)
        sh = ShardedGoalOptimizer(
            mesh=mesh, goal_ids=goals,
            hard_ids=tuple(g for g in goals if g in G.HARD_GOALS),
            enable_heavy_goals=False,
        )
        sh.optimize(state, ctx)          # compile
        _, warm = sh.optimize(state, ctx)
        trace = next(iter(RECORDER.recent(1, kind="optimize")), None)
        assert trace is not None
        assert len(trace.compile_events) == 0, (
            f"warm sharded solve recompiled: {trace.compile_events}"
        )
        # dispatch budget unchanged vs the fused single-device layout
        assert warm.num_dispatches == len(goals) + 4
