"""Movement-volume accounting + dispatch-budget tests.

The reference surfaces proposal movement cost in ``OptimizerResult.java``
(numInterBrokerReplicaMovements / dataToMoveMB / numLeadershipMovements) because
replica movement is the expensive thing its thresholds exist to bound
(BalancingConstraint.java:24-41).  These tests pin that accounting plus the
dispatch budget the async optimizer promises (~#goals + 3 jitted dispatches per
optimize — the host↔device round-trip count that dominates on a tunneled TPU).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow   # full-pipeline compiles; movement accounting
# is also exercised by every bench run (bench.py prints the movement fields)

from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
from cruise_control_tpu.analyzer.optimizer import movement_stats
from cruise_control_tpu.synthetic import SyntheticSpec, generate


def _spread_spec(**kw):
    base = dict(
        num_racks=4, num_brokers=12, num_topics=6, num_partitions=240,
        replication_factor=3, seed=11, mean_disk=0.2, mean_nw_in=0.15,
    )
    base.update(kw)
    return SyntheticSpec(**base)


class TestMovementStats:
    def test_identity_diff_is_zero(self):
        state, _ = generate(_spread_spec())
        m = movement_stats(state, state)
        assert m.num_inter_broker_moves == 0
        assert m.num_intra_broker_moves == 0
        assert m.num_leadership_moves == 0
        assert m.inter_broker_data_to_move == 0.0

    def test_skewed_cluster_movement_is_accounted(self):
        """A skewed cluster produces moves; the accounting must agree with the
        raw placement diff and price them by the moved replicas' disk load."""
        state, _ = generate(_spread_spec(skew_brokers=4))
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        opt = GoalOptimizer(enable_heavy_goals=True)
        final, result = opt.optimize(state, ctx)

        b0 = np.asarray(state.replica_broker)
        b1 = np.asarray(final.replica_broker)
        valid = np.asarray(state.replica_valid)
        moved = valid & (b0 != b1)
        assert result.movement.num_inter_broker_moves == int(moved.sum())
        from cruise_control_tpu.core.resources import Resource

        disk = np.asarray(state.base_load)[:, Resource.DISK]
        expect_bytes = float(disk[moved].sum())
        assert abs(result.movement.inter_broker_data_to_move - expect_bytes) <= (
            1e-6 * max(expect_bytes, 1.0)
        )
        assert result.movement.num_inter_broker_moves > 0

    def test_near_balanced_cluster_moves_nearly_nothing(self):
        """The cost discipline the thresholds encode: a cluster already inside
        every band must not be churned (near-zero movement volume)."""
        # uniform load, no skew, ample headroom → already balanced
        state, _ = generate(
            _spread_spec(distribution="uniform", skew_brokers=0,
                         mean_cpu=0.1, mean_disk=0.1, mean_nw_in=0.05)
        )
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        opt = GoalOptimizer(enable_heavy_goals=True)
        _, result = opt.optimize(state, ctx)
        valid = int(np.asarray(state.replica_valid).sum())
        frac = result.movement.num_inter_broker_moves / max(valid, 1)
        # the count/topic-distribution goals legitimately nudge a random
        # round-robin placement a little; "near-zero" = an order of magnitude
        # under the skewed case's ~70%
        assert frac < 0.10, (
            f"near-balanced cluster relocated {frac:.1%} of replicas "
            f"({result.movement.num_inter_broker_moves}/{valid})"
        )


class TestDispatchBudget:
    def test_optimize_is_one_dispatch_per_goal(self):
        """VERDICT r3 #4: ≤ ~20 jitted dispatches per optimize.  The exact
        fused-mode contract: 1 initial violations + 2 offline phases + 1 per
        goal + 1 trailing full violations (the per-goal steps carry only their
        own scalars)."""
        state, _ = generate(_spread_spec(skew_brokers=4))
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        opt = GoalOptimizer(enable_heavy_goals=True, fuse_goal_dispatch=True)
        _, result = opt.optimize(state, ctx)
        assert result.num_dispatches == len(opt.goal_ids) + 4
        assert result.num_dispatches <= 20
