"""Detector + self-healing tests against the fake backend.

Mirrors the reference's detector test tier (``AnomalyDetectorManagerTest``,
``SlowBrokerFinderTest``) plus the broker-failure integration scenario
(``BrokerFailureIntegrationTest.java:38``: kill broker → self-healing drains it) —
run in-process on :class:`FakeClusterBackend` instead of embedded Kafka.
"""

import time

import numpy as np
import pytest

from cruise_control_tpu.backend import FakeClusterBackend
from cruise_control_tpu.core.resources import Resource
from cruise_control_tpu.detector import (
    AnomalyDetectorManager,
    AnomalyNotifier,
    AnomalyType,
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MaintenanceEvent,
    MaintenanceEventDetector,
    MaintenanceEventType,
    NoopNotifier,
    SelfHealingNotifier,
    TopicReplicationFactorAnomalyFinder,
)
from cruise_control_tpu.detector.detectors import Detector
from cruise_control_tpu.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import (
    BackendMetricSampler,
    LoadMonitor,
    StaticCapacityResolver,
)

CAPACITY = {
    Resource.CPU: 100.0,
    Resource.NW_IN: 1e6,
    Resource.NW_OUT: 1e6,
    Resource.DISK: 1e7,
}
WINDOW_MS = 60_000


def build_cc(num_brokers=6, partitions=24, rf=2, skew=3):
    backend = FakeClusterBackend()
    for b in range(num_brokers):
        backend.add_broker(b, rack=str(b % 3))
    for p in range(partitions):
        reps = [(p % skew), (p % skew + 1) % num_brokers]
        backend.create_partition(("T", p), reps, load=[1.5, 4e3, 6e3, 3e4])
    monitor = LoadMonitor(
        backend,
        BackendMetricSampler(backend),
        StaticCapacityResolver(CAPACITY),
        num_windows=4,
        window_ms=WINDOW_MS,
    )
    executor = Executor(
        backend,
        pause_sampling=monitor.pause_sampling,
        resume_sampling=monitor.resume_sampling,
    )
    from tests.fixtures import service_test_goals

    cc = CruiseControl(
        backend, monitor, executor,
        goal_ids=service_test_goals(), enable_heavy_goals=False,
    )
    cc.start()
    for w in range(6):
        monitor.sample_once(now_ms=(w + 1) * WINDOW_MS)
    return backend, monitor, cc


class TestBrokerFailureDetector:
    def test_detects_and_persists_failure_times(self, tmp_path):
        backend, monitor, cc = build_cc()
        path = str(tmp_path / "failed_brokers.json")
        det = BrokerFailureDetector(backend, path, now_ms=lambda: 12345)
        assert det.run() == []
        backend.kill_broker(1)
        anomalies = det.run()
        assert len(anomalies) == 1
        assert anomalies[0].failed_brokers == {1: 12345}
        # a fresh detector instance (restart) recalls the failure time
        det2 = BrokerFailureDetector(backend, path, now_ms=lambda: 99999)
        anomalies2 = det2.run()
        assert anomalies2[0].failed_brokers == {1: 12345}

    def test_recovered_broker_cleared(self, tmp_path):
        backend, monitor, cc = build_cc()
        det = BrokerFailureDetector(backend, str(tmp_path / "fb.json"))
        backend.kill_broker(2)
        assert det.run()
        backend.restart_broker(2)
        assert det.run() == []


class TestSelfHealingLoop:
    # ~35 s on the 1-core box (self-healing fix = full optimize); nightly slow
    # tier — the notifier/dedupe behavior below stays fast
    @pytest.mark.slow
    def test_broker_failure_grace_period(self, tmp_path):
        """Before the alert threshold the notifier defers (CHECK); past the
        self-healing threshold it fixes (SelfHealingNotifier.onBrokerFailure:228)."""
        backend, monitor, cc = build_cc()
        clock = {"now": 1_000_000}
        notifier = SelfHealingNotifier(
            broker_failure_alert_threshold_ms=10_000,
            broker_failure_self_healing_threshold_ms=20_000,
            now_ms=lambda: clock["now"],
        )
        det = BrokerFailureDetector(
            backend, str(tmp_path / "fb.json"), now_ms=lambda: clock["now"]
        )
        manager = AnomalyDetectorManager(cc, notifier, detectors=[])
        backend.kill_broker(1)
        (anomaly,) = det.run()
        assert manager.handle_anomaly(anomaly) == "CHECK"
        clock["now"] += 25_000
        (anomaly2,) = det.run()
        assert manager.handle_anomaly(anomaly2) == "FIXED"
        # broker 1 drained
        topics = backend.describe_topics()
        for infos in topics.values():
            for i in infos:
                assert 1 not in i.replicas, f"{i.tp} still on dead broker"

    def test_noop_notifier_ignores(self, tmp_path):
        backend, monitor, cc = build_cc()
        det = BrokerFailureDetector(backend, str(tmp_path / "fb.json"))
        manager = AnomalyDetectorManager(cc, NoopNotifier(), detectors=[])
        backend.kill_broker(1)
        (anomaly,) = det.run()
        assert manager.handle_anomaly(anomaly) == "IGNORE"
        assert manager.num_self_healing_started == 0


class TestDiskFailure:
    def test_offline_logdir_detected(self):
        backend = FakeClusterBackend()
        backend.add_broker(0, rack="0", logdirs={"/d0": 1e12, "/d1": 1e12})
        backend.add_broker(1, rack="1")
        backend.kill_logdir(0, "/d1")
        det = DiskFailureDetector(backend)
        (anomaly,) = det.run()
        assert anomaly.failed_disks == {0: ["/d1"]}


class TestGoalViolationDetector:
    # ~90 s on the 1-core box (detector pass compiles its own optimize
    # programs); nightly slow tier — the fix-rebalances path stays fast
    @pytest.mark.slow
    def test_skewed_cluster_reports_violations_and_balancedness(self):
        backend, monitor, cc = build_cc(skew=2)  # heavy skew on brokers 0-1
        det = GoalViolationDetector(cc)
        anomalies = det.run()
        # the skewed start must violate at least the distribution goals
        assert anomalies and anomalies[0].violated_goals
        from cruise_control_tpu.analyzer.optimizer import MAX_BALANCEDNESS_SCORE

        assert det.balancedness_score < MAX_BALANCEDNESS_SCORE

    def test_goal_violation_fix_rebalances(self):
        backend, monitor, cc = build_cc(skew=2)
        det = GoalViolationDetector(cc)
        manager = AnomalyDetectorManager(cc, AnomalyNotifier(), detectors=[])
        (anomaly,) = det.run()
        assert manager.handle_anomaly(anomaly) == "FIXED"
        assert anomaly.fix_result.execution is not None
        # re-detection after the fix finds fewer violations
        anomalies_after = det.run()
        before = len(anomaly.violated_goals)
        after = len(anomalies_after[0].violated_goals) if anomalies_after else 0
        assert after < before


class TestTopicAnomaly:
    def test_rf_mismatch_detected(self):
        backend = FakeClusterBackend()
        for b in range(3):
            backend.add_broker(b, rack=str(b))
        backend.create_partition(("good", 0), [0, 1, 2], load=[1, 1, 1, 1])
        backend.create_partition(("bad", 0), [0], load=[1, 1, 1, 1])
        det = TopicReplicationFactorAnomalyFinder(backend, target_rf=3)
        (anomaly,) = det.run()
        assert anomaly.bad_topics == {"bad": 1}


class TestMaintenanceEvents:
    def test_dedupe_and_fix(self):
        backend, monitor, cc = build_cc()
        det = MaintenanceEventDetector()
        e1 = MaintenanceEvent(event_type=MaintenanceEventType.REBALANCE)
        e2 = MaintenanceEvent(event_type=MaintenanceEventType.REBALANCE)
        det.submit(e1)
        det.submit(e2)
        out = det.run()
        assert len(out) == 1  # idempotence cache dedupes


class TestManagerState:
    def test_state_reporting(self, tmp_path):
        backend, monitor, cc = build_cc()
        notifier = SelfHealingNotifier()
        det = BrokerFailureDetector(backend, str(tmp_path / "fb.json"))
        manager = AnomalyDetectorManager(cc, notifier, detectors=[(det, 60.0)])
        backend.kill_broker(1)
        manager.run_detector_once(det)
        st = manager.state()
        assert st.queue_size == 1
        assert st.recent_anomalies["BROKER_FAILURE"]
        assert st.self_healing_enabled["GOAL_VIOLATION"] is True


class TestInitialDetectionPass:
    """Satellite (ISSUE 12): detectors used to sleep a full interval before
    their FIRST pass (`_detector_loop` entered `self._stop.wait(interval_s)`
    straight away) — a broker that died during the restart window went
    unnoticed for up to a whole cadence.  With
    ``anomaly.detection.initial.pass`` each detector runs one immediate pass
    as soon as the readiness probe opens."""

    class _CountingDetector(Detector):
        name = "CountingDetector"

        def __init__(self):
            self.runs = 0

        def run(self):
            self.runs += 1
            return []

    def _poll(self, fn, timeout_s=10.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if fn():
                return True
            time.sleep(0.02)
        return False

    def test_immediate_pass_fires_once_ready(self):
        backend, monitor, cc = build_cc()
        det = self._CountingDetector()
        ready = {"ok": False}
        manager = AnomalyDetectorManager(
            cc, NoopNotifier(), detectors=[(det, 3_600.0)],
            initial_pass=True, ready_probe=lambda: ready["ok"],
        )
        manager.start_detection()
        try:
            time.sleep(0.3)
            assert det.runs == 0          # gate closed: no pass yet
            ready["ok"] = True
            assert self._poll(lambda: det.runs >= 1)
            time.sleep(0.3)
            assert det.runs == 1          # exactly one immediate pass
        finally:
            manager.shutdown()

    def test_default_behavior_unchanged_without_initial_pass(self):
        backend, monitor, cc = build_cc()
        det = self._CountingDetector()
        manager = AnomalyDetectorManager(
            cc, NoopNotifier(), detectors=[(det, 3_600.0)]
        )
        manager.start_detection()
        try:
            time.sleep(0.4)
            assert det.runs == 0          # first pass waits the interval
        finally:
            manager.shutdown()

    def test_raising_probe_reads_as_not_ready(self):
        backend, monitor, cc = build_cc()
        det = self._CountingDetector()

        def probe():
            raise RuntimeError("backend down")

        manager = AnomalyDetectorManager(
            cc, NoopNotifier(), detectors=[(det, 3_600.0)],
            initial_pass=True, ready_probe=probe,
        )
        manager.start_detection()
        try:
            time.sleep(0.3)
            assert det.runs == 0
        finally:
            manager.shutdown()

    def test_app_wires_probe_from_readiness_ladder(self, tmp_path):
        from cruise_control_tpu.app import CruiseControlTpuApp

        backend = FakeClusterBackend()
        backend.add_broker(0, rack="0")
        backend.create_partition(("T", 0), [0], load=[1, 1, 1, 1])
        app = CruiseControlTpuApp(
            {
                "webserver.http.port": 0,
                "anomaly.detection.interval.ms": 3_600_000,
                "sample.store.class":
                    "cruise_control_tpu.monitor.samplestore.NoopSampleStore",
            },
            backend=backend,
        )
        assert app.anomaly_manager.initial_pass is True
        assert app.anomaly_manager.ready_probe is not None
        # the probe is the readiness ladder: closed until the app starts
        assert app.anomaly_manager.ready_probe() is False
