"""Config kernel tests (reference behavior: ConfigDef/AbstractConfig unit tests)."""

import pytest

from cruise_control_tpu.core.config import (
    Config,
    ConfigDef,
    ConfigException,
    Importance,
    Password,
    Type,
    in_range,
    in_values,
)


def _def():
    return (
        ConfigDef()
        .define("num.windows", Type.INT, 5, Importance.HIGH, "window count", in_range(1, None))
        .define("ratio", Type.DOUBLE, 0.5, validator=in_range(0.0, 1.0))
        .define("name", Type.STRING, "cc")
        .define("enabled", Type.BOOLEAN, True)
        .define("goals", Type.LIST, "a,b,c")
        .define("secret", Type.PASSWORD, "hunter2")
        .define("required.key", Type.INT)
    )


def test_defaults_and_overrides():
    cfg = Config(_def(), {"required.key": 7, "num.windows": "10"})
    assert cfg.get_int("num.windows") == 10
    assert cfg.get_double("ratio") == 0.5
    assert cfg.get_boolean("enabled") is True
    assert cfg.get_list("goals") == ["a", "b", "c"]
    assert cfg.get_int("required.key") == 7


def test_missing_required_raises():
    with pytest.raises(ConfigException, match="required.key"):
        Config(_def(), {})


def test_validator_rejects_out_of_range():
    with pytest.raises(ConfigException, match="ratio"):
        Config(_def(), {"required.key": 1, "ratio": 1.5})


def test_bool_and_list_parsing():
    cfg = Config(_def(), {"required.key": 1, "enabled": "false", "goals": ["x", "y"]})
    assert cfg.get_boolean("enabled") is False
    assert cfg.get_list("goals") == ["x", "y"]


def test_bad_type_raises():
    with pytest.raises(ConfigException):
        Config(_def(), {"required.key": "not-an-int"})


def test_unknown_keys_tolerated_and_reported():
    cfg = Config(_def(), {"required.key": 1, "mystery.key": "z"})
    assert cfg.unknown() == ["mystery.key"]


def test_password_redacted():
    cfg = Config(_def(), {"required.key": 1})
    assert isinstance(cfg.get("secret"), Password)
    assert cfg.to_dict()["secret"] == Password.HIDDEN
    assert "hunter2" not in repr(cfg.get("secret"))


def test_in_values_validator():
    d = ConfigDef().define("mode", Type.STRING, "fast", validator=in_values("fast", "full"))
    with pytest.raises(ConfigException):
        Config(d, {"mode": "other"})
    assert Config(d, {"mode": "full"}).get("mode") == "full"


def test_merge_and_double_define():
    base = ConfigDef().define("a", Type.INT, 1)
    other = ConfigDef().define("a", Type.INT, 99).define("b", Type.INT, 2)
    base.merge(other)
    cfg = Config(base, {})
    assert cfg.get("a") == 1  # first definition wins
    assert cfg.get("b") == 2
    with pytest.raises(ConfigException):
        base.define("a", Type.INT, 3)


def test_configured_instance():
    d = ConfigDef().define("impl", Type.CLASS, "cruise_control_tpu.core.config.Password")
    cfg = Config(d, {})
    with pytest.raises(ConfigException):
        cfg.get_configured_instance("impl", dict)  # wrong expected type
