"""Deterministic test clusters.

Port of the *behavioral fixtures* in the reference's test tree
(``cruise-control/src/test/java/.../common/DeterministicCluster.java:32`` and
``TestConstants.java``): tiny explicit clusters with hand-set loads, used for exact
assertions on model math and goal outcomes.  Loads are [CPU, NW_IN, NW_OUT, DISK].
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

from cruise_control_tpu.core.resources import Resource
from cruise_control_tpu.model.cluster import ClusterModel

# TestConstants.java:36-38,105-107
TYPICAL_CPU_CAPACITY = 100.0
LARGE_BROKER_CAPACITY = 300000.0
MEDIUM_BROKER_CAPACITY = 200000.0
SMALL_BROKER_CAPACITY = 10.0

BROKER_CAPACITY: Dict[Resource, float] = {
    Resource.CPU: TYPICAL_CPU_CAPACITY,
    Resource.DISK: LARGE_BROKER_CAPACITY,
    Resource.NW_IN: LARGE_BROKER_CAPACITY,
    Resource.NW_OUT: MEDIUM_BROKER_CAPACITY,
}

# DeterministicCluster.java:48-60
RACK_BY_BROKER = {0: "0", 1: "0", 2: "1"}
RACK_BY_BROKER2 = {0: "0", 1: "1", 2: "1"}
RACK_BY_BROKER4 = {0: "0", 1: "1", 2: "2", 3: "0", 4: "1", 5: "2"}

T1, T2 = "T1", "T2"


def load(cpu: float, nw_in: float, nw_out: float, disk: float):
    return [cpu, nw_in, nw_out, disk]


def homogeneous_cluster(
    rack_by_broker: Mapping[int, str],
    capacity: Optional[Mapping[Resource, float]] = None,
    logdirs: Optional[Mapping[str, float]] = None,
) -> ClusterModel:
    """All brokers share one capacity spec (DeterministicCluster.getHomogeneousCluster)."""
    cluster = ClusterModel()
    for broker_id, rack in sorted(rack_by_broker.items()):
        cluster.create_broker(rack, broker_id, capacity or BROKER_CAPACITY, logdirs=logdirs)
    return cluster


def unbalanced() -> ClusterModel:
    """Two racks, three brokers, two 1-replica partitions both on broker 0
    (DeterministicCluster.unbalanced, :200)."""
    cluster = homogeneous_cluster(RACK_BY_BROKER)
    half = load(
        TYPICAL_CPU_CAPACITY / 2,
        LARGE_BROKER_CAPACITY / 2,
        MEDIUM_BROKER_CAPACITY / 2,
        LARGE_BROKER_CAPACITY / 2,
    )
    for topic in (T1, T2):
        cluster.create_replica(0, (topic, 0), 0, True)
        cluster.set_replica_load(0, (topic, 0), half)
    return cluster


def unbalanced2() -> ClusterModel:
    """unbalanced() plus four more 1-replica partitions, 3 on broker 0, 1 on broker 1
    (DeterministicCluster.unbalanced2)."""
    cluster = unbalanced()
    half = load(
        TYPICAL_CPU_CAPACITY / 2,
        LARGE_BROKER_CAPACITY / 2,
        MEDIUM_BROKER_CAPACITY / 2,
        LARGE_BROKER_CAPACITY / 2,
    )
    placements = [(1, (T1, 1)), (0, (T2, 1)), (0, (T1, 2)), (0, (T2, 2))]
    for broker, tp in placements:
        cluster.create_replica(broker, tp, 0, True)
        cluster.set_replica_load(broker, tp, half)
    return cluster


def unbalanced_with_a_follower() -> ClusterModel:
    """unbalanced() with a follower of T1-0 on broker 2
    (DeterministicCluster.unbalancedWithAFollower)."""
    cluster = unbalanced()
    cluster.create_replica(2, (T1, 0), 1, False)
    cluster.set_replica_load(
        2,
        (T1, 0),
        load(TYPICAL_CPU_CAPACITY / 8, LARGE_BROKER_CAPACITY / 2, 0.0, LARGE_BROKER_CAPACITY / 2),
    )
    return cluster


def rack_aware_satisfiable() -> ClusterModel:
    """Two racks, three brokers, one partition with replicas on brokers 0 and 1 —
    both in rack '0', so rack-awareness is violated but fixable by moving one replica
    to rack '1' (DeterministicCluster.rackAwareSatisfiable, :227)."""
    cluster = homogeneous_cluster(RACK_BY_BROKER)
    cluster.create_replica(0, (T1, 0), 0, True)
    cluster.create_replica(1, (T1, 0), 1, False)
    cluster.set_replica_load(0, (T1, 0), load(40.0, 100.0, 130.0, 75.0))
    cluster.set_replica_load(1, (T1, 0), load(5.0, 100.0, 0.0, 75.0))
    return cluster


def rack_aware_unsatisfiable() -> ClusterModel:
    """rack_aware_satisfiable() plus a third replica: 3 replicas, only 2 racks —
    rack-awareness cannot be satisfied (DeterministicCluster.rackAwareUnsatisfiable)."""
    cluster = rack_aware_satisfiable()
    cluster.create_replica(2, (T1, 0), 2, False)
    cluster.set_replica_load(2, (T1, 0), load(5.0, 100.0, 0.0, 75.0))
    return cluster


#: trimmed goal list for service-layer tests (api/detector/provision/aux):
#: their subject is the surrounding plumbing, not goal math — compiling the
#: full 16-goal pipeline per module costs ~4 min on the 1-core CI box, and
#: the goal kernels have their own dedicated test modules.
def service_test_goals():
    from cruise_control_tpu.analyzer import goals_base as G

    return (
        G.RACK_AWARE,
        G.REPLICA_CAPACITY,
        G.DISK_CAPACITY,
        G.CPU_CAPACITY,
        G.REPLICA_DISTRIBUTION,
        G.DISK_USAGE_DIST,
    )
