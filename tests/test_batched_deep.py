"""Acceptance: the batched deep sweep on the config1 cluster.

The ISSUE contract, asserted end to end from the obs flight record:

* a warm 16-scenario ``deep_sweep`` over the full (non-heavy) default goal
  list on the config1 cluster (3 brokers / 20 partitions — the gate's
  ``config1`` tier shape) completes in ≤ (#goals + 6) total compiled
  dispatches with ZERO XLA compile events;
* its per-scenario verdicts equal the sequential per-scenario loop
  (``deep_sweep(batched=False)`` — one full ``optimize()`` per scenario).

This lives in its own module so its compile budget (the batched and unbatched
full-goal-list program sets) does not contend with other modules' executables
(conftest clears jit caches between modules).
"""

import pytest

from cruise_control_tpu.analyzer import goals_base as G
from cruise_control_tpu.obs import RECORDER
from cruise_control_tpu.sim import Scenario, deep_sweep
from cruise_control_tpu.synthetic import SyntheticSpec, generate

# ~5 min on the 1-core box (compiles BOTH full-goal-list program sets);
# nightly slow tier + the gate's config1 dispatch budget cover the contract
pytestmark = pytest.mark.slow

#: deep_sweep runs GoalOptimizer(enable_heavy_goals=False): the heavy [B,T]
#: goals drop out of the default list, and the dispatch budget follows
N_GOALS = len([g for g in G.DEFAULT_GOAL_ORDER if g not in G.HEAVY_GOALS])


@pytest.fixture(scope="module")
def config1():
    """The gate's config1 tier shape (obs/gate._build_config1)."""
    spec = SyntheticSpec(
        num_racks=2, num_brokers=3, num_topics=2, num_partitions=20,
        replication_factor=2, distribution="exponential", skew_brokers=1,
        mean_cpu=0.25, mean_disk=0.2, mean_nw_in=0.15, mean_nw_out=0.15,
        seed=3,
    )
    return generate(spec)[0]


def sixteen_scenarios():
    """16 mixed hypotheticals, all inside the 8-broker bucket (adds ≤ 3)."""
    out = []
    for i in range(16):
        out.append(
            Scenario(
                name=f"s{i}",
                add_brokers=i % 4,
                kill_brokers=(i % 3,) if i % 5 == 0 else (),
                load_factor=1.0 + 0.05 * i,
                capacity_factors=(1.0, 1.0, 1.0, 1.5) if i % 7 == 0 else
                                 (1.0, 1.0, 1.0, 1.0),
            )
        )
    return out


class TestConfig1DeepSweepAcceptance:
    def test_warm_16_scenario_sweep_meets_dispatch_and_compile_budget(
        self, config1
    ):
        scs = sixteen_scenarios()
        seq = deep_sweep(config1, scs, batched=False)     # the reference path
        deep_sweep(config1, scs)                           # batched warmup
        r = deep_sweep(config1, scs)                       # measured warm sweep

        # one goal-order group ⇒ #goals + 4 dispatches, inside the +6 budget
        assert r.sweep_size == 16
        assert r.num_dispatches == N_GOALS + 4
        assert r.num_dispatches <= N_GOALS + 6
        assert r.bucket_hit
        # vs B × (#goals + 4) for the sequential loop
        assert seq.num_dispatches == 16 * (N_GOALS + 4)

        # the obs flight record is the evidence, not the return value
        trace = RECORDER.recent(limit=1, kind="simulate")[0]
        assert trace.attrs["deep"] is True
        assert trace.attrs["sweep_size"] == 16
        assert trace.attrs["num_dispatches"] == r.num_dispatches
        assert trace.total_dispatches == r.num_dispatches
        assert trace.compile_events == [], (
            "warm batched deep sweep must cause zero XLA compiles: "
            + str(trace.compile_events)
        )

        # per-scenario results equal the sequential path
        for v, w in zip(r.scenarios, seq.scenarios):
            assert v.name == w.name
            assert v.violations == w.violations, v.name
            assert v.balancedness == w.balancedness, v.name
            assert v.movement == w.movement, v.name
            assert v.verdict == w.verdict, v.name
            assert v.provision_status == w.provision_status, v.name
            assert v.satisfiable == w.satisfiable, v.name
