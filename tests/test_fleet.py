"""Fleet-controller tier: N tenant control loops, one batched control plane.

What this module locks down (cruise_control_tpu/fleet/):

* the batched dispatch contract — one vmapped drift probe per goal-order
  group per fleet tick, the grouped incremental optimize inside the
  ``#goals + 4`` budget, ZERO XLA compiles on warm ticks (asserted from the
  ``fleet_tick`` flight record), and per-tenant proposals BIT-IDENTICAL to a
  standalone single-tenant controller fed the same shifts;
* grouping as correctness — tenants with differing goal orders never share a
  stack (``stack_arrays`` refuses outright; the fleet groups first);
* durability composition — a pre-fleet ``journal.dir/controller`` WAL is
  adopted as the ``default`` tenant's namespace on first fleet startup, with
  recovery/fencing/publish/restart losing no record and doubling no publish;
* hierarchy — cross-tenant drain arbitration (budget, rotation, stagger),
  tenant → admission-tier threading and per-tenant quota isolation;
* the FLEET REST endpoint, client methods and ``cctpu fleet`` CLI.

The slow 32-tenant acceptance test runs the exact harness that commits
``benchmarks/BENCH_FLEET_cpu.json`` (fleet/bench.py, also the ``fleet`` gate
tier) — the fast tests here use 2-3 tenants with ``max_rounds_per_tick=1``
so the batched programs stay cheap to compile on the 1-core CI box.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from cruise_control_tpu.analyzer import goals_base as G
from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.api.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRefused,
)
from cruise_control_tpu.controller import bench as cbench
from cruise_control_tpu.controller.loop import (
    ContinuousController,
    ControllerConfig,
)
from cruise_control_tpu.controller.standing import (
    ControllerJournal,
    StandingProposalSet,
)
from cruise_control_tpu.core.journal import Journal
from cruise_control_tpu.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.fleet import (
    RESERVED_TENANT_NAMES,
    FleetConfig,
    FleetController,
    adopt_legacy_namespace,
)
from cruise_control_tpu.fleet import bench as fbench
from cruise_control_tpu.model import arrays as A
from cruise_control_tpu.obs import RECORDER

#: one tick shape for the whole module (mirrors tests/test_controller.py):
#: max_rounds=1 keeps the batched per-goal programs cheap to compile
FLEET_TICK_CFG = dict(
    tick_interval_s=3_600.0,   # cadence off — drift (or force) triggers
    drift_threshold=1.0,
    max_rounds_per_tick=1,
)

WINDOW_MS = cbench.WINDOW_MS


def _props(n: int = 2):
    return [
        ExecutionProposal(
            tp=("T", i), partition_size=1.0, old_leader=0,
            old_replicas=(0, 1), new_replicas=(0, 2),
        )
        for i in range(n)
    ]


def _standing(version: int, n: int = 2) -> StandingProposalSet:
    return StandingProposalSet(
        version=version, created_ms=1_000 + version, trigger="drift",
        drift=2.0, proposals=_props(n),
    )


def _shift_cluster(backend, controller, victim: int, prev_hot):
    """Reset the previous hot set, overload the partitions the controller's
    TRACKED placement hosts on ``victim`` (same recipe as the bench)."""
    for tp in prev_hot:
        backend.set_partition_load(tp, list(cbench.BASE_LOAD))
    hot = cbench.hot_partitions_on(controller, victim)
    for tp in hot:
        backend.set_partition_load(tp, [0.2, 50.0, 50.0, cbench.HOT_DISK])
    return hot


def _feed(monitors, now_ms: int) -> int:
    """Two windows so the shifted samples land in a STABLE window on every
    monitor (the still-filling window is excluded by the aggregator)."""
    now_ms += WINDOW_MS
    for m in monitors:
        m.sample_once(now_ms=now_ms)
    now_ms += WINDOW_MS
    for m in monitors:
        m.sample_once(now_ms=now_ms)
    return now_ms


def _proposal_keys(standing: StandingProposalSet):
    return [
        (p.tp, p.old_leader, tuple(p.old_replicas), tuple(p.new_replicas))
        for p in standing.proposals
    ]


# -- the batched dispatch contract + bit-identity vs the single-tenant loop ---


class TestFleetTick:
    @pytest.mark.slow  # compile-heavy: 3 fleet tenants + 3 standalone twins;
    # CI's fleet step runs it by name (ci_local.sh / ci.yml)
    def test_warm_tick_census_and_bit_identity(self, tmp_path):
        """One vmapped probe for all tenants, optimize within budget, zero
        warm compiles — and every tenant's published proposals bit-identical
        to a standalone single-tenant controller fed the same shifts."""
        N = 3
        fleet = FleetController(
            config=FleetConfig(**FLEET_TICK_CFG),
            journal_dir=str(tmp_path / "journal"),
        )
        tenants = []            # (backend, monitor)
        for t in range(N):
            backend, monitor, cc = cbench.build_cluster()
            fleet.add_tenant(f"t{t}", cc)
            tenants.append((backend, monitor))
        # standalone twins: identical seeded clusters, identical tick shape
        solos = []              # (backend, monitor, controller)
        for t in range(N):
            backend, monitor, controller, _ = cbench.build_harness(
                config=ControllerConfig(**FLEET_TICK_CFG)
            )
            solos.append((backend, monitor, controller))
        now = cbench.warm_window_clock()
        for w in range(cbench.NUM_WINDOWS + 2):
            ts = now + w * WINDOW_MS
            for _, monitor in tenants:
                monitor.sample_once(now_ms=ts)
        now += (cbench.NUM_WINDOWS + 2) * WINDOW_MS

        fleet.warm()
        for _, _, sctl in solos:
            sctl.warm_start()

        fleet_hot = [[] for _ in range(N)]
        solo_hot = [[] for _ in range(N)]

        def shift_all(victim):
            for t in range(N):
                frt = fleet.tenant(f"t{t}")
                fleet_hot[t] = _shift_cluster(
                    tenants[t][0], frt.controller, victim, fleet_hot[t]
                )
                solo_hot[t] = _shift_cluster(
                    solos[t][0], solos[t][2], victim, solo_hot[t]
                )

        # shift 1: settles initial placements + pays any first-tick host jits
        shift_all(0)
        now = _feed([m for _, m in tenants] + [m for _, m, _ in solos], now)
        assert fleet.maybe_tick() is not None
        for _, _, sctl in solos:
            sctl.maybe_tick()

        # shift 2: the measured warm tick
        shift_all(1)
        now = _feed([m for _, m in tenants] + [m for _, m, _ in solos], now)
        attrs = fleet.maybe_tick()
        assert attrs is not None

        # census — identical tenants share ONE group and ONE vmapped probe
        assert attrs["groups"] == 1
        assert attrs["probe_dispatches"] == 1
        assert attrs["tenants_per_dispatch"] == N
        assert attrs["published"] == N
        assert attrs["num_dispatches"] <= len(cbench.GOALS) + 4
        # the 0-compile contract, from the fleet tick's flight record
        trace = next(iter(RECORDER.recent(1, kind="fleet_tick")), None)
        assert trace is not None
        assert len(trace.compile_events) == 0
        assert trace.attrs["num_dispatches"] == attrs["num_dispatches"]

        # bit-identity: each tenant's standing set vs its standalone twin
        for t in range(N):
            fctl = fleet.tenant(f"t{t}").controller
            sctl = solos[t][2]
            assert sctl.maybe_tick() is not None
            assert fctl.standing is not None and sctl.standing is not None
            assert fctl.standing.version == sctl.standing.version
            assert _proposal_keys(fctl.standing) == _proposal_keys(sctl.standing)
            # ...and the tracked placements the next tick will probe
            np.testing.assert_array_equal(
                np.asarray(fctl._state_host.replica_broker),
                np.asarray(sctl._state_host.replica_broker),
            )

        # per-tenant metric labels reached the registry
        from cruise_control_tpu.obs.exporter import render_prometheus

        page = render_prometheus()
        assert 'family="Fleet",sensor="tenant.t0.' in page
        assert 'family="Fleet",sensor="coordinator.ticks"' in page
        fleet.stop()

    @pytest.mark.slow  # compiles a second goal-order group end to end
    def test_mixed_goal_orders_group_separately(self):
        """Satellite regression: tenants under different goal orders must
        never share a stack — the fleet groups them apart (two probe
        dispatches, both still publish) and ``stack_arrays`` refuses a
        mixed-order batch outright."""
        alt_goals = (G.RACK_AWARE, G.REPLICA_CAPACITY, G.DISK_CAPACITY)
        fleet = FleetController(config=FleetConfig(**FLEET_TICK_CFG))
        b_full, m_full, cc_full = cbench.build_cluster()
        fleet.add_tenant("full", cc_full)
        b_alt, m_alt, _ = cbench.build_cluster()
        cc_alt = CruiseControl(
            b_alt, m_alt, Executor(b_alt),
            goal_ids=alt_goals,
            hard_ids=tuple(g for g in alt_goals if g in G.HARD_GOALS),
        )
        fleet.add_tenant("trim", cc_alt)
        now = cbench.warm_window_clock()
        for w in range(cbench.NUM_WINDOWS + 2):
            ts = now + w * WINDOW_MS
            m_full.sample_once(now_ms=ts)
            m_alt.sample_once(now_ms=ts)
        now += (cbench.NUM_WINDOWS + 2) * WINDOW_MS
        fleet.warm()

        k_full = fleet._group_key(fleet.tenant("full"))
        k_trim = fleet._group_key(fleet.tenant("trim"))
        assert k_full != k_trim

        with pytest.raises(ValueError, match="differing goal orders"):
            A.stack_arrays(
                [
                    fleet.tenant("full").controller._state_host,
                    fleet.tenant("trim").controller._state_host,
                ],
                goal_orders=[cbench.GOALS, alt_goals],
            )

        _shift_cluster(b_full, fleet.tenant("full").controller, 0, [])
        _shift_cluster(b_alt, fleet.tenant("trim").controller, 0, [])
        now = _feed([m_full, m_alt], now)
        attrs = fleet.maybe_tick()
        assert attrs is not None
        assert attrs["groups"] == 2
        assert attrs["probe_dispatches"] == 2
        assert attrs["published"] == 2
        fleet.stop()

    @pytest.mark.slow
    def test_acceptance_32_tenants(self):
        """The ISSUE's acceptance run — the exact harness behind
        benchmarks/BENCH_FLEET_cpu.json and the ``fleet`` gate tier."""
        m = fbench.run_bench()
        assert m["published"] == m["num_tenants"] * m["shifts"]
        assert m["groups"] == 1
        assert m["warm_probe_dispatches"] == 1
        assert m["warm_tick_dispatches"] <= m["dispatch_budget"]
        assert m["warm_compile_events"] == 0
        assert m["tenants_per_dispatch"] == m["num_tenants"]


# -- tenant registry + coordinator plumbing (host-only) -----------------------


class TestFleetRegistry:
    def test_tenant_name_validation(self):
        fleet = FleetController()
        _, _, cc = cbench.build_cluster()
        for bad in ("", "a/b", " padded ", *RESERVED_TENANT_NAMES):
            with pytest.raises(ValueError):
                fleet.add_tenant(bad, cc)
        fleet.add_tenant("ok", cc)
        with pytest.raises(ValueError, match="duplicate"):
            _, _, cc2 = cbench.build_cluster()
            fleet.add_tenant("ok", cc2)
        assert fleet.tenant_names == ["ok"]

    def test_pause_resume_fleet_and_single_tenant(self):
        fleet = FleetController()
        _, _, cc = cbench.build_cluster()
        fleet.add_tenant("a", cc)
        fleet.pause("ops")
        assert fleet.paused and fleet.maybe_tick() is None
        fleet.resume("ops done")
        assert not fleet.paused
        fleet.pause("noisy", tenant="a")
        assert fleet.tenant("a").controller.paused
        assert not fleet.paused            # fleet itself keeps running
        fleet.resume(tenant="a")
        assert not fleet.tenant("a").controller.paused

    def test_drain_arbitration_budget_rotation_and_stagger(self):
        """The coordinator grants at most ``max_concurrent_drains`` per tick
        in tick-rotated order; a tenant inside its stagger window defers."""
        clock = [1_000.0]
        fleet = FleetController(
            config=FleetConfig(
                **FLEET_TICK_CFG, execute=True,
                max_concurrent_drains=1, drain_stagger_s=300.0,
            ),
            clock=lambda: clock[0],
        )
        drained = []
        runtimes = []
        for name in ("a", "b"):
            _, _, cc = cbench.build_cluster()
            rt = fleet.add_tenant(name, cc)
            rt.controller._drain_standing = (
                lambda fh, _n=name: drained.append(_n) or True
            )
            runtimes.append(rt)
        live = [(rt, None, None) for rt in runtimes]

        for rt in runtimes:
            rt.pending_drain = (object(), object())
        drains, deferrals = fleet._arbitrate_drains(live)
        assert (drains, deferrals) == (1, 1)
        assert drained == ["a"]
        assert all(rt.pending_drain is None for rt in runtimes)

        # next tick: rotation starts at b; a is ALSO inside its stagger
        fleet._tick_count = 1
        for rt in runtimes:
            rt.pending_drain = (object(), object())
        drains, deferrals = fleet._arbitrate_drains(live)
        assert (drains, deferrals) == (1, 1)
        assert drained == ["a", "b"]

        # stagger: nobody re-drains until the window passes
        for rt in runtimes:
            rt.pending_drain = (object(), object())
        drains, deferrals = fleet._arbitrate_drains(live)
        assert (drains, deferrals) == (0, 2)
        clock[0] += 301.0
        for rt in runtimes:
            rt.pending_drain = (object(), object())
        drains, _ = fleet._arbitrate_drains(live)
        assert drains == 1
        assert drained == ["a", "b", "b"]   # rotation still starts at b

        # execute off: pending sets are cleared without any drain
        fleet.cfg.execute = False
        for rt in runtimes:
            rt.pending_drain = (object(), object())
        assert fleet._arbitrate_drains(live) == (0, 0)
        assert all(rt.pending_drain is None for rt in runtimes)
        assert drained == ["a", "b", "b"]   # no drain ran with execute off


# -- satellite: legacy journal.dir/controller adoption ------------------------


class TestLegacyMigration:
    def _write_legacy(self, jdir: str) -> None:
        legacy = ControllerJournal(Journal(os.path.join(jdir, "controller")))
        legacy.fence(1)
        legacy.published(_standing(3))
        legacy.published(_standing(4, n=3))
        legacy.close()

    def test_adopt_moves_namespace_once(self, tmp_path):
        jdir = str(tmp_path / "journal")
        self._write_legacy(jdir)
        assert adopt_legacy_namespace(jdir) is True
        assert not os.path.exists(os.path.join(jdir, "controller"))
        assert os.path.isdir(os.path.join(jdir, "default"))
        # idempotent: nothing left to adopt
        assert adopt_legacy_namespace(jdir) is False
        # a fresh dir with nothing to adopt is a no-op too
        assert adopt_legacy_namespace(str(tmp_path / "empty")) is False

    def test_recover_fence_publish_restart_no_loss_no_double(self, tmp_path):
        """The satellite's migration drill: old single-tenant layout →
        fleet startup adopts it → recovery resumes the exact standing set
        under a bumped fence → a new publish supersedes → restart replays
        exactly one live set (no record loss, no double-publish)."""
        jdir = str(tmp_path / "journal")
        self._write_legacy(jdir)

        fleet = FleetController(journal_dir=jdir)
        _, _, cc = cbench.build_cluster()
        rt = fleet.add_tenant("default", cc)
        assert not os.path.exists(os.path.join(jdir, "controller"))
        replayed = fleet.recover()
        # epoch record + published v3 + published v4, all preserved
        assert replayed == 3
        ctl = rt.controller
        assert ctl.standing is not None and ctl.standing.version == 4
        assert len(ctl.standing.proposals) == 3
        assert _proposal_keys(ctl.standing) == _proposal_keys(_standing(4, n=3))
        # restart-and-adopt fences epoch+1: the legacy writer is deposed
        assert ctl.journal.epoch == 2

        # publish under the adopted namespace (what tick_commit appends)
        ctl.journal.published(_standing(5, n=1))
        ctl.journal.invalidated(4, "superseded by v5")
        fleet.stop()

        # restart: same records, a newer fence, exactly ONE live set
        fleet2 = FleetController(journal_dir=jdir)
        _, _, cc2 = cbench.build_cluster()
        rt2 = fleet2.add_tenant("default", cc2)
        assert fleet2.recover() > 0
        ctl2 = rt2.controller
        assert ctl2.standing is not None and ctl2.standing.version == 5
        assert len(ctl2.standing.proposals) == 1
        assert ctl2.journal.epoch == 3
        fleet2.stop()

        # the compacted WAL holds the live set once — no doubled publish
        records = Journal(os.path.join(jdir, "default")).replay()
        published = [r for r in records if r.get("type") == "published"]
        assert [r["version"] for r in published] == [5]


# -- satellite: tenant → admission tier + per-tenant quota isolation ----------


class TestTenantAdmission:
    def test_quota_shed_isolates_tenants_and_counts_exactly(self):
        adm = AdmissionController(
            AdmissionConfig(max_concurrent=10, max_tasks_per_principal=1)
        )
        fleet = FleetController(admission=adm)
        _, _, cc_a = cbench.build_cluster()
        _, _, cc_b = cbench.build_cluster()
        fleet.add_tenant("tenantA", cc_a, tier=3)
        fleet.add_tenant("tenantB", cc_b, tier=0)
        # tenant → principal tier threading (set_tier_override)
        assert adm.tier_of(None, True, principal="tenantA") == 3
        assert adm.tier_of(None, True, principal="tenantB") == 0
        assert fleet.tenant("tenantA").tier == 3

        # tenantA saturates its quota; its SECOND acquire sheds instantly
        # (the server maps AdmissionRefused → 429 + Retry-After)
        ticket_a = adm.acquire("tenantA", "REBALANCE")
        with pytest.raises(AdmissionRefused) as exc:
            adm.acquire("tenantA", "REBALANCE")
        assert exc.value.reason == "principal-quota"
        assert exc.value.retry_after_s > 0

        # ...while tenantB's REBALANCE admits in the same tick window
        ticket_b = adm.acquire("tenantB", "REBALANCE")
        snap = adm.snapshot()
        assert snap["activeByPrincipal"] == {"tenantA": 1, "tenantB": 1}
        assert snap["admitted"] == 2 and snap["shed"] == 1
        assert snap["shedByReason"] == {"principal-quota": 1}
        # counters account EXACTLY per tenant
        assert adm.shed_by_principal == {"tenantA": 1}
        ticket_a.release()
        ticket_b.release()
        snap = adm.snapshot()
        assert snap["active"] == 0 and snap["activeByPrincipal"] == {}


# -- the FLEET endpoint, client methods, CLI ----------------------------------


GOAL_NAMES_CSV = ",".join(G.GOAL_NAMES[g] for g in cbench.GOALS)


class TestFleetEndpoint:
    @pytest.fixture()
    def served(self, tmp_path):
        from cruise_control_tpu.app import CruiseControlTpuApp
        from cruise_control_tpu.backend import FakeClusterBackend
        from cruise_control_tpu.client import CruiseControlClient
        from cruise_control_tpu.monitor.capacity import StaticCapacityResolver

        backend = FakeClusterBackend()
        for b in range(cbench.BROKERS):
            backend.add_broker(b, rack=str(b % cbench.RACKS))
        for p in range(cbench.PARTITIONS):
            backend.create_partition(
                ("T", p), [p % cbench.BROKERS, (p + 1) % cbench.BROKERS],
                load=list(cbench.BASE_LOAD),
            )
        props = {
            "partition.metrics.window.ms": WINDOW_MS,
            "num.partition.metrics.windows": cbench.NUM_WINDOWS,
            "metric.sampling.interval.ms": 3_600_000,
            "anomaly.detection.interval.ms": 3_600_000,
            "anomaly.detection.initial.pass": False,
            "broker.capacity.config.resolver.class":
                "cruise_control_tpu.monitor.capacity.StaticCapacityResolver",
            "sample.store.class":
                "cruise_control_tpu.monitor.samplestore.NoopSampleStore",
            "webserver.http.port": 0,
            "min.valid.partition.ratio": 0.5,
            "default.goals": GOAL_NAMES_CSV,
            "fleet.enable": True,
            "fleet.tick.interval.ms": 3_600_000,
            "fleet.max.rounds.per.tick": 1,
            "fleet.tenants": "beta",
            "fleet.tenant.tiers": "default:2,beta:0",
            # keep every tenant un-warmable: the endpoint tests exercise the
            # REST surface, not device work (no windows → warm_start defers)
            "demo.bootstrap.on.start": False,
            "journal.dir": str(tmp_path / "journal"),
        }
        app = CruiseControlTpuApp(props, backend=backend)
        app.monitor.capacity_resolver = StaticCapacityResolver(cbench.CAPACITY)
        app.start(serve_http=True)
        client = CruiseControlClient(
            f"http://127.0.0.1:{app.port}", poll_timeout_s=600.0
        )
        yield app, client
        app.stop()

    def test_status_pause_resume_state_and_schema(self, served):
        from cruise_control_tpu.api.schemas import validate_endpoint
        from cruise_control_tpu.client import ClientError

        app, client = served
        assert app.controller is None      # fleet mode replaces the solo loop
        assert client.controller_status()["enabled"] is False

        body = client.fleet_status()
        validate_endpoint("FLEET", body)
        assert body["enabled"] is True
        assert body["tenantCount"] == 2
        assert set(body["tenants"]) == {"default", "beta"}
        assert body["tenants"]["default"]["tier"] == 2
        assert body["tenants"]["beta"]["tier"] == 0
        assert body["config"]["maxRoundsPerTick"] == 1

        # ?tenant= narrows to one tenant's block; unknown tenants 404
        body = client.fleet_status(tenant="beta")
        validate_endpoint("FLEET", body)
        assert body["tenant"] == "beta"
        with pytest.raises(ClientError) as exc:
            client.fleet_status(tenant="nope")
        assert exc.value.status == 404

        # fleet-wide pause/resume over POST
        body = client.fleet_pause(reason="ops")
        validate_endpoint("FLEET", body)
        assert body["paused"] is True and app.fleet.paused
        assert client.fleet_resume()["paused"] is False

        # per-tenant pause leaves the fleet (and the other tenant) running
        body = client.fleet_pause(reason="noisy", tenant="beta")
        assert body["paused"] is False
        assert body["tenants"]["beta"]["paused"] is True
        assert app.fleet.tenant("beta").controller.paused
        client.fleet_resume(tenant="beta")
        assert not app.fleet.tenant("beta").controller.paused

        with pytest.raises(ClientError) as exc:
            client._post("fleet", action="bogus")
        assert exc.value.status == 400

        # STATE carries the Fleet block; /metrics carries the fleet sensors
        state = client.state()
        assert state["Fleet"]["state"] == "running"
        assert state["Fleet"]["tenantCount"] == 2
        validate_endpoint("STATE", state)

    def test_cli_fleet_subcommand(self, served, capsys):
        from cruise_control_tpu.client import cli

        app, client = served
        url = f"http://127.0.0.1:{app.port}"
        assert cli.main(["-a", url, "fleet", "status"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["enabled"] is True and out["tenantCount"] == 2
        assert cli.main(["-a", url, "fleet", "pause", "--tenant", "beta",
                         "--reason", "cli drill"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["tenants"]["beta"]["paused"] is True
        assert cli.main(["-a", url, "fleet", "resume", "--tenant", "beta"]) == 0
        capsys.readouterr()
        assert not app.fleet.tenant("beta").controller.paused
