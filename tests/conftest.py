"""Test environment: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip hardware is not available in CI; all sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, per the project
testing contract.  This must run before any ``import jax`` in the test session.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
