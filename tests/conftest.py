"""Test environment: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip hardware is not available in CI; all sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, per the project
testing contract.  This must run before any ``import jax`` in the test session.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's accelerator hook overrides the env var by writing
# "axon,cpu" straight into jax's config after import, so a plain
# JAX_PLATFORMS=cpu still tries the (possibly unreachable) TPU tunnel first
# and can block the whole test session on backend init.  Forcing the config
# value after import is the only override that sticks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable jax's persistent compilation cache here.  On this CPU
# the AOT loader deserializes cached executables with a machine-feature
# mismatch ("+prefer-no-scatter ... could lead to SIGILL") and has segfaulted
# inside compilation_cache.get_executable_and_time mid-suite.  Recompiling is
# slower but reliable.  The suite also constructs CruiseControlTpuApp, whose
# shell wires core.compile_cache from $CC_TPU_COMPILE_CACHE — strip the var so
# an ambient setting (CI exports it for the bench steps) cannot enable the
# real cache mid-suite through the app tests.
os.environ.pop("CC_TPU_COMPILE_CACHE", None)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Release compiled executables between test modules.

    Each module compiles its own shape variants of the solver phases; keeping
    every executable loaded for the whole session has crashed XLA's CPU
    compiler (SIGSEGV in backend_compile_and_load) late in the run.
    """
    yield
    jax.clear_caches()
