"""TRAIN plumbing + provisioning verdict tests.

* TRAIN (LinearRegressionModelParameters / ModelParameters.java): fitted CPU
  weights must be CONSUMED — the monitor's next cluster model derives follower
  CPU and leadership deltas from them, not from the static defaults.
* Provisioning (ProvisionResponse/ProvisionRecommendation.java): the optimizer
  sizes the cluster — UNDER with a broker deficit when hard goals fail, OVER
  with a removable surplus on a near-idle cluster, RIGHT_SIZED otherwise; the
  goal-violation detector feeds non-RIGHT_SIZED verdicts to the Provisioner.
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
from cruise_control_tpu.analyzer.optimizer import provision_verdict
from cruise_control_tpu.backend import FakeClusterBackend
from cruise_control_tpu.core.resources import Resource
from cruise_control_tpu.detector.detectors import GoalViolationDetector
from cruise_control_tpu.detector.provisioner import BasicProvisioner
from cruise_control_tpu.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.model.model_utils import (
    DEFAULT_CPU_WEIGHTS,
    CpuModelWeights,
    follower_cpu_from_leader_load,
)
from cruise_control_tpu.monitor import (
    BackendMetricSampler,
    LoadMonitor,
    StaticCapacityResolver,
)
from cruise_control_tpu.synthetic import SyntheticSpec, generate

CAPACITY = {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6, Resource.DISK: 1e7}
WINDOW_MS = 60_000


def build_cc(num_brokers=4, partitions=12):
    backend = FakeClusterBackend()
    for b in range(num_brokers):
        backend.add_broker(b, rack=str(b % 2))
    for p in range(partitions):
        reps = [p % 2, (p % 2 + 1) % num_brokers]
        backend.create_partition(("T", p), reps, load=[1.5, 4e3, 6e3, 3e4])
    monitor = LoadMonitor(
        backend, BackendMetricSampler(backend), StaticCapacityResolver(CAPACITY),
        num_windows=4, window_ms=WINDOW_MS,
    )
    executor = Executor(backend)
    from tests.fixtures import service_test_goals

    cc = CruiseControl(
        backend, monitor, executor,
        goal_ids=service_test_goals(), enable_heavy_goals=False,
    )
    cc.start()
    for w in range(6):
        monitor.sample_once(now_ms=(w + 1) * WINDOW_MS)
    return backend, monitor, cc


class TestTrainPlumbing:
    def test_fitted_weights_are_consumed_by_next_model(self):
        backend, monitor, cc = build_cc()
        fitted = CpuModelWeights(0.5, 0.3, 0.2)
        monitor.set_cpu_model(fitted)
        assert monitor.cpu_weights == fitted
        # the sampler's processor follows too
        assert monitor.sampler.processor.cpu_weights == fitted

        model = monitor.cluster_model()
        # find a follower replica and check its CPU matches the fitted formula
        state, maps = model.to_arrays()
        lead = np.asarray(
            state.partition_leader[np.asarray(state.replica_partition)]
            == np.arange(state.num_replicas)
        )
        valid = np.asarray(state.replica_valid)
        followers = np.nonzero(valid & ~lead)[0]
        assert len(followers) > 0
        base = np.asarray(state.base_load)
        rp = np.asarray(state.replica_partition)
        ld = np.asarray(state.leadership_delta)
        f = int(followers[0])
        p = rp[f]
        leader_cpu = base[f, Resource.CPU] + ld[p, Resource.CPU]
        leader_out = ld[p, Resource.NW_OUT]
        nw_in = base[f, Resource.NW_IN]
        expect = float(
            follower_cpu_from_leader_load(nw_in, leader_out, leader_cpu, fitted)
        )
        assert base[f, Resource.CPU] == pytest.approx(expect, rel=1e-4)
        # and it differs from what the static defaults would have produced
        static = float(
            follower_cpu_from_leader_load(nw_in, leader_out, leader_cpu, DEFAULT_CPU_WEIGHTS)
        )
        assert abs(expect - static) > 1e-9

    def test_train_endpoint_adopts_weights(self):
        backend, monitor, cc = build_cc()
        ok = cc.train_cpu_model(0, 10 * WINDOW_MS)
        assert ok
        assert monitor.cpu_weights == cc.trained_cpu_weights
        assert monitor.cpu_weights != DEFAULT_CPU_WEIGHTS


class TestProvisionVerdicts:
    def test_near_idle_cluster_is_over_provisioned(self):
        spec = SyntheticSpec(
            num_racks=6, num_brokers=12, num_topics=4, num_partitions=60,
            replication_factor=2, distribution="uniform", seed=3,
            mean_cpu=0.01, mean_disk=0.01, mean_nw_in=0.01, mean_nw_out=0.01,
        )
        state, maps = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        verdict = provision_verdict(state, ctx, violated_hard=[])
        assert verdict.status == "OVER_PROVISIONED"
        assert verdict.num_brokers_to_remove > 0

    def test_busy_cluster_is_right_sized(self):
        spec = SyntheticSpec(
            num_racks=6, num_brokers=12, num_topics=4, num_partitions=120,
            replication_factor=3, distribution="uniform", seed=3,
            mean_cpu=0.5, mean_disk=0.25, mean_nw_in=0.2, mean_nw_out=0.3,
        )
        state, maps = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        verdict = provision_verdict(state, ctx, violated_hard=[])
        assert verdict.status == "RIGHT_SIZED"

    def test_under_provisioned_reports_broker_deficit(self):
        spec = SyntheticSpec(
            num_racks=4, num_brokers=4, num_topics=4, num_partitions=80,
            replication_factor=3, distribution="uniform", seed=5,
            mean_cpu=0.4, mean_disk=0.35, mean_nw_in=0.2, mean_nw_out=0.2,
        )
        state, maps = generate(spec)
        ctx = GoalContext.build(state.num_topics, state.num_brokers)
        verdict = provision_verdict(state, ctx, violated_hard=["DiskCapacityGoal"])
        assert verdict.status == "UNDER_PROVISIONED"
        assert verdict.num_brokers_to_add >= 1

    # ~95 s on the 1-core box (detector pass + provisioner = full optimize
    # chain); nightly slow tier — the direct verdict tests above stay fast
    @pytest.mark.slow
    def test_detector_feeds_provisioner_on_violation(self):
        backend, monitor, cc = build_cc()
        prov = BasicProvisioner()
        det = GoalViolationDetector(cc, provisioner=prov)
        det.run()
        if det.last_result is not None and det.last_result.provision.status != "RIGHT_SIZED":
            assert prov.history, "provisioner should have been consulted"
            assert det.last_provisioner_result is not None
