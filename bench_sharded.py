#!/usr/bin/env python
"""Sharded-solver measurement: wall-clock + goal-step collective accounting.

ROADMAP #3 / ISSUE 14: quantify what the replica-sharded solver actually costs
against the single-device solver on the same host, and pin the goal step's
collective census so the 120-all-reduce GSPMD regression can't silently
return.  On the CI box the 8 mesh devices are virtual (ONE physical core), so
sharded wall-clock measures *overhead*, not speedup — every per-shard fixed
cost runs serialized ×8, which floors the honest virtual-device ratio strictly
above 1.0; on real multi-chip hardware the same script reports the speedup.

Robustness contract (the MULTICHIP rc-124 fix): the artifact JSON is written
AHEAD of every stage and refreshed after it, so even a SIGKILL from an outer
``timeout -k`` leaves a parseable artifact with the stages that did finish and
``"ok": false`` — never an empty file.  ``--deadline-s`` additionally stops
between stages when the budget is spent.

Stages:
  census   — compile ONE sharded RackAware goal step; count collectives in the
             LOGICAL program (the communication design — single-digit by
             construction) and in the compiled HLO text (continuity with the
             historical artifact; XLA CPU loop-widening clones inflate it);
  single   — warm single-device optimize wall (compile run first);
  sharded  — warm shard_map optimize wall + proposal identity + warm-recompile
             check from the flight recorder;
  gspmd    — optional A/B (--gspmd): the legacy auto-partitioned path's wall
             for attribution (CC_TPU_SHARDED_SPMD=0).

Usage: python bench_sharded.py [--brokers N] [--partitions N] [--rf N]
           [--devices N] [--deadline-s S] [--gspmd] [--out FILE]
"""

import argparse
import collections
import json
import os
import re
import time

COLLECTIVE_RE = r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
# the logical census regex is parallel.spmd.LOGICAL_COLLECTIVE_RE — imported
# in _run() AFTER the env/platform setup (the module imports jax)


def census(text: str, pattern: str) -> dict:
    c = collections.Counter(m.group(1) for m in re.finditer(pattern, text))
    return dict(sorted(c.items()))


class Artifact:
    """Write-ahead artifact: every mutation lands on disk immediately, so an
    outer kill leaves the last completed stage on record instead of rc-only."""

    def __init__(self, path, doc):
        self.path = path
        self.doc = doc
        self.flush()

    def update(self, **kw):
        self.doc.update(kw)
        self.flush()

    def stage_done(self, name):
        self.doc.setdefault("stages_completed", []).append(name)
        self.flush()

    def flush(self):
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.doc, f, indent=1)
        os.replace(tmp, self.path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=32)
    ap.add_argument("--partitions", type=int, default=6_000)
    ap.add_argument("--rf", type=int, default=4)
    ap.add_argument("--racks", type=int, default=4)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--deadline-s", type=float, default=900.0)
    ap.add_argument("--gspmd", action="store_true",
                    help="also time the legacy GSPMD auto-partitioned path")
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    t_start = time.monotonic()

    def remaining():
        return args.deadline_s - (time.monotonic() - t_start)

    # virtual device mesh on CPU unless a real multi-chip backend exists
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    art = Artifact(args.out, {
        "metric": (
            f"sharded_vs_single_wall_s_{args.brokers}brokers_"
            f"{args.partitions}partitions_rf{args.rf}"
        ),
        "unit": "s",
        "ok": False,
        "stage": "importing",
        "stages_completed": [],
        "devices": args.devices,
        "virtual_devices": True,
        "args": {
            "brokers": args.brokers, "partitions": args.partitions,
            "rf": args.rf, "racks": args.racks, "devices": args.devices,
        },
    })
    try:
        _run(args, art, remaining, jax)
    except Exception as e:  # noqa: BLE001 - the artifact IS the error channel
        art.update(ok=False, error=f"{type(e).__name__}: {e}")
        print(json.dumps(art.doc))
        raise
    print(json.dumps(art.doc))


def _run(args, art, remaining, jax) -> None:
    from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
    from cruise_control_tpu.analyzer import goals_base as G
    from cruise_control_tpu.analyzer.goal_rounds import GOAL_ROUNDS
    from cruise_control_tpu.obs.recorder import RECORDER
    from cruise_control_tpu.parallel import solver_mesh
    from cruise_control_tpu.parallel.mesh import REPLICA_AXIS, replicate, shard_state
    from cruise_control_tpu.parallel.solver import sharded_steps
    from cruise_control_tpu.parallel.spmd import (
        LOGICAL_COLLECTIVE_RE,
        SpmdInfo,
    )
    from cruise_control_tpu.synthetic import SyntheticSpec, generate

    spec = SyntheticSpec(
        num_racks=args.racks,
        num_brokers=args.brokers,
        num_topics=100,
        num_partitions=args.partitions,
        replication_factor=args.rf,
        distribution="exponential",
        skew_brokers=max(args.brokers // 4, 1),
        mean_cpu=0.25, mean_disk=0.2, mean_nw_in=0.15, mean_nw_out=0.15,
        seed=11, build_maps=False,
    )
    state, _ = generate(spec)
    ctx = GoalContext.build(state.num_topics, state.num_brokers)
    goal_ids = (G.RACK_AWARE, G.REPLICA_CAPACITY, G.DISK_CAPACITY, G.CPU_CAPACITY)

    # --- stage: census of one sharded goal step (RackAware) -----------------
    art.update(stage="census")
    devices = jax.devices()[: args.devices]
    mesh = solver_mesh(devices)
    sstate = shard_state(state, mesh)
    sctx = replicate(ctx, mesh)
    spmd = SpmdInfo(
        axis=REPLICA_AXIS, n=len(devices), global_R=sstate.num_replicas
    )
    steps = sharded_steps(mesh, spmd)
    lowered = steps["goal_step"].lower(
        sstate, sctx,
        gid=G.RACK_AWARE,
        round_fns=GOAL_ROUNDS[G.RACK_AWARE],
        max_rounds=2000, enable_heavy=False,
        prior_ids=(), admit_ids=(G.RACK_AWARE,),
    )
    logical = census(lowered.as_text(), LOGICAL_COLLECTIVE_RE)
    t0 = time.monotonic()
    compiled = lowered.compile()
    compile_s = time.monotonic() - t0
    compiled_census = census(compiled.as_text(), COLLECTIVE_RE)
    art.update(
        # the LOGICAL census is the headline: collectives the program DESIGN
        # issues per goal step (the O(1) contract); the compiled count keeps
        # continuity with the historical artifact, inflated by XLA CPU's
        # while-loop widening/cloning of the same logical ops
        collectives_per_goal_step=logical,
        collectives_per_goal_step_total=sum(logical.values()),
        collectives_per_goal_step_compiled=compiled_census,
        goal_step_compile_s=round(compile_s, 1),
    )
    art.stage_done("census")
    if remaining() <= 0:
        art.update(stage="deadline", error="deadline before single stage")
        return

    # --- stage: single-device wall ------------------------------------------
    art.update(stage="single")
    single = GoalOptimizer(goal_ids=goal_ids, enable_heavy_goals=False)
    single.optimize(state, ctx)                    # compile
    t0 = time.monotonic()
    _, r1 = single.optimize(state, ctx)
    single_s = time.monotonic() - t0
    art.update(
        single_device_s=round(single_s, 3),
        total_moves=r1.total_moves,
        num_dispatches=r1.num_dispatches,
    )
    art.stage_done("single")
    if remaining() <= 0:
        art.update(stage="deadline", error="deadline before sharded stage")
        return

    # --- stage: sharded wall + identity + warm recompiles -------------------
    art.update(stage="sharded")
    from cruise_control_tpu.parallel import ShardedGoalOptimizer

    sharded = ShardedGoalOptimizer(
        mesh=mesh, goal_ids=goal_ids, enable_heavy_goals=False
    )
    sharded.optimize(state, ctx)                   # compile
    t0 = time.monotonic()
    _, r8 = sharded.optimize(state, ctx)
    sharded_s = time.monotonic() - t0
    warm_trace = next(iter(RECORDER.recent(1, kind="optimize")), None)
    art.update(
        value=round(sharded_s, 3),
        overhead_x=round(sharded_s / max(single_s, 1e-9), 2),
        proposal_identity=r1.total_moves == r8.total_moves,
        sharded_dispatches=r8.num_dispatches,
        warm_compile_events=(
            len(warm_trace.compile_events) if warm_trace else None
        ),
        spmd_path=sharded.use_spmd,
    )
    art.stage_done("sharded")

    # --- stage: optional GSPMD A/B ------------------------------------------
    if args.gspmd and remaining() > 0:
        art.update(stage="gspmd")
        os.environ["CC_TPU_SHARDED_SPMD"] = "0"
        try:
            legacy = ShardedGoalOptimizer(
                mesh=mesh, goal_ids=goal_ids, enable_heavy_goals=False
            )
            legacy.optimize(state, ctx)
            t0 = time.monotonic()
            _, rl = legacy.optimize(state, ctx)
            gspmd_s = time.monotonic() - t0
            art.update(
                gspmd_s=round(gspmd_s, 3),
                gspmd_overhead_x=round(gspmd_s / max(single_s, 1e-9), 2),
                gspmd_identity=rl.total_moves == r1.total_moves,
            )
            art.stage_done("gspmd")
        finally:
            os.environ.pop("CC_TPU_SHARDED_SPMD", None)

    art.update(stage="done", ok=True)


if __name__ == "__main__":
    main()
