#!/usr/bin/env python
"""Sharded-solver measurement: wall-clock + compiled-HLO collective counts.

SURVEY §7 step 5 / VERDICT r3 #8: quantify what GSPMD actually emits for the
replica-sharded solver and compare sharded vs single-device wall-clock on the
same host.  On the CI box the 8 mesh devices are virtual (one physical core),
so sharded wall-clock measures *overhead*, not speedup — the honest quantity
this script reports alongside the collective census; on a real v5e-8 the same
script gives the speedup.

Usage: python bench_sharded.py [--brokers N] [--partitions N] [--devices N] [--out FILE]
"""

import argparse
import collections
import json
import os
import re
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=256)
    ap.add_argument("--partitions", type=int, default=25_000)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    # virtual device mesh on CPU unless a real multi-chip backend exists
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from cruise_control_tpu.analyzer import GoalContext, GoalOptimizer
    from cruise_control_tpu.analyzer import goals_base as G
    from cruise_control_tpu.analyzer.goal_rounds import GOAL_ROUNDS
    from cruise_control_tpu.analyzer.optimizer import _goal_step
    from cruise_control_tpu.parallel import ShardedGoalOptimizer, solver_mesh
    from cruise_control_tpu.parallel.mesh import replicate, shard_state
    from cruise_control_tpu.synthetic import SyntheticSpec, generate

    spec = SyntheticSpec(
        num_racks=16,
        num_brokers=args.brokers,
        num_topics=200,
        num_partitions=args.partitions,
        replication_factor=3,
        distribution="exponential",
        skew_brokers=args.brokers // 4,
        mean_cpu=0.25, mean_disk=0.2, mean_nw_in=0.15, mean_nw_out=0.15,
        seed=11, build_maps=False,
    )
    state, _ = generate(spec)
    ctx = GoalContext.build(state.num_topics, state.num_brokers)
    goal_ids = (G.RACK_AWARE, G.REPLICA_CAPACITY, G.DISK_CAPACITY, G.CPU_CAPACITY)

    # --- collective census of one sharded goal step (RackAware) -------------
    devices = jax.devices()[: args.devices]
    mesh = solver_mesh(devices)
    sstate = shard_state(state, mesh)
    sctx = replicate(ctx, mesh)
    lowered = _goal_step.lower(
        sstate, sctx,
        gid=G.RACK_AWARE,
        round_fns=GOAL_ROUNDS[G.RACK_AWARE],
        max_rounds=2000, enable_heavy=False,
        prior_ids=(), admit_ids=(G.RACK_AWARE,),
    )
    t0 = time.monotonic()
    compiled = lowered.compile()
    compile_s = time.monotonic() - t0
    hlo = compiled.as_text()
    census = collections.Counter(
        m.group(1)
        for m in re.finditer(
            r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)\b",
            hlo,
        )
    )

    # --- wall-clock: sharded vs single-device ------------------------------
    def run(opt, st, cx):
        final, result = opt.optimize(st, cx)
        return result

    single = GoalOptimizer(goal_ids=goal_ids, enable_heavy_goals=False)
    run(single, state, ctx)                        # compile
    t0 = time.monotonic()
    r1 = run(single, state, ctx)
    single_s = time.monotonic() - t0

    sharded = ShardedGoalOptimizer(
        mesh=mesh, goal_ids=goal_ids, enable_heavy_goals=False
    )
    run(sharded, state, ctx)                       # compile
    t0 = time.monotonic()
    r8 = run(sharded, state, ctx)
    sharded_s = time.monotonic() - t0

    out = {
        "metric": f"sharded_vs_single_wall_s_{args.brokers}brokers_{args.partitions}partitions",
        "value": round(sharded_s, 3),
        "unit": "s",
        "single_device_s": round(single_s, 3),
        "overhead_x": round(sharded_s / max(single_s, 1e-9), 2),
        "devices": args.devices,
        "virtual_devices": True,
        "collectives_per_goal_step": dict(census),
        "goal_step_compile_s": round(compile_s, 1),
        "proposal_identity": r1.total_moves == r8.total_moves,
        "total_moves": r1.total_moves,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
